"""SchedulerCache: the stateful cluster mirror.

Mirrors reference pkg/scheduler/cache/cache.go:
- One mutex over Jobs/Nodes/Queues/PriorityClasses maps (:73-115).
- Snapshot() deep-clones ready nodes, queues, and jobs that carry a scheduling
  spec, resolving job priority from PriorityClasses (:612-659).
- Bind/Evict mutate the mirror under lock, then fire the side effect
  asynchronously; failures trigger a rate-limited resync of the task
  (:421-522, :588-608).
- Deleted jobs are cleaned up via a queue once terminated (:556-585).

Watch ingest comes from a ClusterAPI watch (the informer analog); tests feed
the event-handler entry points directly.
"""

from __future__ import annotations

import logging
import os
import queue
import threading
import time
from collections import deque
from concurrent.futures import ThreadPoolExecutor
from typing import Dict, Iterable, List, Optional

logger = logging.getLogger(__name__)

from ..api import (
    ClusterInfo,
    JobInfo,
    Node,
    NodeInfo,
    Pod,
    PodCondition,
    PodGroup,
    PriorityClass,
    Queue,
    QueueInfo,
    TaskInfo,
    TaskStatus,
)
from ..obs.tracer import TRACER, span as _obs_span
from ..api.objects import DEFAULT_SCHEDULER_NAME
from ..cluster import ADDED, DELETED, MODIFIED, ClusterAPI
from ..utils.lockdebug import witness_writes, wrap_lock
from .event_handlers import EventHandlersMixin
from .interface import Binder, Cache, Evictor, StatusUpdater, VolumeBinder
from .util import job_terminated, shadow_pod_group


class CacheFencedError(RuntimeError):
    """A bind/evict was refused because the cache is fenced: the loop
    watchdog (or the leader-election layer) declared this process a
    deposed leader, and a deposed leader must not mutate the cluster —
    a successor holding the lease may already be scheduling the same
    tasks (doc/design/robustness.md)."""


class DefaultBinder(Binder):
    """reference cache.go:117-135 (POST /bind analog)"""

    def __init__(self, cluster: ClusterAPI):
        self.cluster = cluster

    def bind(self, pod: Pod, hostname: str) -> None:
        self.cluster.bind_pod(pod, hostname)


class DefaultEvictor(Evictor):
    """reference cache.go:137-148 (pod DELETE analog)"""

    def __init__(self, cluster: ClusterAPI):
        self.cluster = cluster

    def evict(self, pod: Pod) -> None:
        self.cluster.delete_pod(pod)


class DefaultStatusUpdater(StatusUpdater):
    """reference cache.go:151-197"""

    def __init__(self, cluster: ClusterAPI):
        self.cluster = cluster

    def update_pod_condition(self, pod: Pod, condition: PodCondition) -> None:
        self.cluster.update_pod_condition(pod, condition)

    def update_pod_group(self, pg: PodGroup) -> None:
        self.cluster.update_pod_group(pg)


class DefaultVolumeBinder(VolumeBinder):
    """Assume/bind volume lifecycle (reference cache.go:200-268).

    ``allocate_volumes`` assumes the pod's unbound claims onto the chosen
    node (conflicting assumptions fail the allocation, like
    AssumePodVolumes); ``task.volume_ready`` records whether every claim
    was already bound. ``bind_volumes`` then waits — up to ``bind_timeout``
    seconds, the reference's 30s — for the PV-controller analog to bind
    the assumed claims, raising TimeoutError on expiry so the dispatch
    fails and the task re-enters the resync path.

    Without a cluster (standalone decision-core use), volumes are
    instantly assumable, preserving the previous no-op behavior."""

    def __init__(self, cluster: Optional[ClusterAPI] = None,
                 bind_timeout: float = 30.0):
        self.cluster = cluster
        self.bind_timeout = bind_timeout

    def allocate_volumes(self, task: TaskInfo, hostname: str) -> None:
        if self.cluster is None or not task.pod.spec.volume_claims:
            task.volume_ready = True
            return
        # ClusterAPI's default treats volumes as instantly assumable;
        # InProcessCluster implements the real assume lifecycle.
        task.volume_ready = self.cluster.assume_pod_volumes(
            task.pod, hostname
        )

    def bind_volumes(self, task: TaskInfo) -> None:
        if task.volume_ready or self.cluster is None:
            return  # cache.go:214-217: ready volumes are not re-bound
        if not self.cluster.wait_pod_volumes_bound(
            task.pod, self.bind_timeout
        ):
            raise TimeoutError(
                f"volumes of {task.namespace}/{task.name} not bound "
                f"within {self.bind_timeout}s"
            )
        task.volume_ready = True

    def release_volumes(self, task: TaskInfo) -> None:
        """Drop the task's claim assumptions after a failed bind so the
        next cycle can place it (or a competitor) elsewhere."""
        if self.cluster is not None:
            self.cluster.release_pod_volumes(task.pod)


def _pool_entry(obj):
    """COW snapshot-pool entry for a job/node: ``(source version, clone,
    clone version)``. snapshot() reuses the clone while BOTH versions
    still match (source unchanged since the clone was cut, clone not
    mutated by the session it was handed to). Sole constructor of the
    entry shape — snapshot() and the bind-bookkeeping prewarm must stay
    in lockstep on this invariant."""
    clone = obj.clone()
    return (obj._ver, clone, clone._ver)


class SchedulerCache(Cache, EventHandlersMixin):
    def __init__(
        self,
        cluster: Optional[ClusterAPI] = None,
        scheduler_name: str = DEFAULT_SCHEDULER_NAME,
        default_queue: str = "default",
        binder: Optional[Binder] = None,
        evictor: Optional[Evictor] = None,
        status_updater: Optional[StatusUpdater] = None,
        volume_binder: Optional[VolumeBinder] = None,
        enable_priority_class: bool = True,
    ):
        # Named for the KBT_LOCK_DEBUG order-asserting harness (raw
        # locks when the flag is off — wrap_lock is identity then).
        self.mutex = wrap_lock("cache.mutex", threading.RLock())
        self.cluster = cluster
        self.scheduler_name = scheduler_name
        self.default_queue = default_queue
        self.enable_priority_class = enable_priority_class

        self.jobs: Dict[str, JobInfo] = {}
        self.nodes: Dict[str, NodeInfo] = {}
        self.queues: Dict[str, QueueInfo] = {}
        self.priority_classes: Dict[str, PriorityClass] = {}
        self.default_priority: int = 0
        self.default_priority_class: Optional[PriorityClass] = None

        self.binder = binder or (DefaultBinder(cluster) if cluster else None)
        self.evictor = evictor or (DefaultEvictor(cluster) if cluster else None)
        self.status_updater = status_updater or (
            DefaultStatusUpdater(cluster) if cluster else None
        )
        self.volume_binder = volume_binder or DefaultVolumeBinder(cluster)

        # Rate-limited retry queues (reference cache.go:588-608, :556-585).
        # Items carry a retry count; re-queues back off exponentially.
        self.err_tasks: "queue.Queue[tuple]" = queue.Queue()
        self.deleted_jobs: "queue.Queue[tuple]" = queue.Queue()
        self._base_retry_delay = 0.05
        self._max_retry_delay = 5.0
        # Poisoned-task cap: a task whose reconcile fails this many
        # times is dropped terminally (counted + named in the job's
        # unschedulable verdict) instead of circulating in the resync
        # queue forever — the reference rate-limits but never gives up,
        # which turns one poisoned task into permanent queue churn.
        self._max_resync_attempts = int(
            os.environ.get("KBT_RESYNC_MAX_ATTEMPTS", "8")
        )
        self._dispatch = self._build_dispatch()

        # COW snapshot pool: {key: (src_ver, clone, clone_ver)} per kind
        # (see snapshot()).
        self._snap_pool: tuple = ({}, {})
        # Job/node names touched since the last snapshot (stamped by the
        # event handlers and the bind bookkeeping under the mutex,
        # drained into ClusterInfo.dirty_jobs/dirty_nodes by snapshot()):
        # the cheap churn ledger the incremental tensorize stats report.
        self._dirty_jobs: set = set()
        self._dirty_nodes: set = set()
        # NARROW ledger: names whose only mutations since the last
        # snapshot were the scheduler's OWN bind bookkeeping (idle/used/
        # task-count moved by exactly the per-node deltas the apply
        # phase computed; releasing/capacity/labels/taints untouched,
        # job scalar-resource names untouched). Third-party watch
        # events stamp the FULL sets above; snapshot() reports
        # narrow = narrow - full so a name that saw both stays
        # conservatively full-dirty. Consumed by the delta-aware
        # tensorize + predicate caches (solver/snapshot.py,
        # plugins/predicates.py) to patch only the changed columns
        # instead of tripping the bulk-dirty full rebuild.
        self._dirty_jobs_alloc: set = set()
        self._dirty_nodes_alloc: set = set()
        # FULL-dirty backlog: names drained by snapshot() but not yet
        # ABSORBED by a tensorize refresh (cache.note_full_absorbed).
        # A cycle can drain the ledger and then never tensorize (a
        # deferred micro cycle, an error before the action, no ready
        # nodes) — if the dropped full-dirty name were later stamped
        # narrow, the delta-aware patch would treat a third-party
        # mutation as allocation-only and leave releasing/capacity/
        # static-verdict columns stale. The backlog keeps reporting a
        # name FULL until a refresh actually consumed it.
        self._full_backlog_jobs: set = set()
        self._full_backlog_nodes: set = set()
        # Monotone snapshot generation: the warm-solve state machine
        # (solver/warm.py) requires CONSECUTIVE snapshots — a cycle
        # whose ledger drained without a warm save invalidates the
        # carried verdicts.
        self._snap_gen = 0
        # Incremental-snapshot state: the previous snapshot's job/node
        # dicts (reused + delta-patched), the running sum of ready-node
        # allocatables, and the aligned verification fingerprint
        # (_SnapFingerprint) that detects EXACTLY which mirror objects
        # or pool clones moved since — no trust in any reporting.
        self._last_snap_jobs: Optional[Dict[str, JobInfo]] = None
        self._last_snap_nodes: Optional[Dict[str, NodeInfo]] = None
        self._snap_total_allocatable = None
        self._snap_fp: Optional[tuple] = None
        self._snap_fp_priority_gen = -1
        # Lazy name->fingerprint-position maps ([jobs, nodes]) for the
        # micro-snapshot ledger verification; rebuilt on demand whenever
        # the fingerprint name lists grew or were refreshed.
        self._snap_fp_index: list = [None, None]
        # Session-clone touch ledger: clone names whose _ver a session
        # bumped (Session/Statement mutators report via
        # note_clones_touched at close). Together with the dirty sets
        # this names every position the micro fast-verification must
        # recheck; drained by snapshot() with the other ledgers.
        self._touched_clone_jobs: set = set()
        self._touched_clone_nodes: set = set()
        # Forensics: how many snapshots took the ledger-verified micro
        # fast path vs the full O(n) fingerprint compare.
        self.snap_ledger_verifies = 0
        self.snap_full_verifies = 0
        # Priority-class generation: job priority is resolved from the
        # class map at snapshot time, so any class change forces the
        # full pool walk (the per-job priority recheck).
        self._priority_gen = 0
        # Event-driven micro-cycles: an arrival listener (Scheduler.run
        # installs a threading.Event setter) fired whenever a pending
        # pod of ours lands in the mirror.
        self._arrival_listener = None
        # Cross-session plugin fold store (plugins/drf.py,
        # plugins/proportion.py): per-plugin caches of open-time fold
        # results keyed on snapshot-clone identity + _ver, so a
        # steady-state micro open recomputes only the churned jobs'
        # contributions instead of the whole O(jobs) fold. Entries are
        # self-invalidating (a mutated job gets a fresh clone, missing
        # the identity compare), so no coordination with the snapshot
        # machinery is needed.
        self.plugin_fold: dict = {}

        # --- event-stream integrity (doc/design/robustness.md) ---------
        # Per-object resourceVersion memos + stream gap tracking,
        # guarded by self.mutex (the ingest path already serializes on
        # it). A versioning cluster (InProcessCluster) delivers each
        # watch event with a monotone rv; the guards absorb duplicate,
        # stale, and out-of-order delivery (counted in
        # cache_event_anomalies_total{kind}) and detect DROPPED events
        # as persistent holes in the rv stream — repaired by a bounded,
        # rate-limited relist through the drain_resync_queue seam
        # instead of a process restart. rv-less events (direct handler
        # calls in tests, list replay, KubeCluster's opaque string rvs)
        # bypass the guards entirely.
        self._watch_rv: Dict[tuple, int] = {}
        self._watch_deleted: deque = deque()
        self._stream_max_rv = 0
        # True once a stream position is established (start_ingest's
        # list adoption, or the first admitted event): only then is a
        # jump past max+1 a HOLE rather than a mid-stream attach.
        self._stream_baselined = False
        self._stream_missing: set = set()
        self._stream_missing_prev: set = set()
        self._event_anomalies: Dict[str, int] = {}
        self._anomaly_flush: list = []
        self._relist_pending = False
        # Injectable clock for relist rate limiting: the simulator
        # installs its virtual clock so record and replay gate relists
        # identically; production uses the monotonic wall clock.
        self._relist_clock = time.monotonic
        self._relist_last: Optional[float] = None
        self._relist_min_interval = float(
            os.environ.get("KBT_RELIST_MIN_INTERVAL", "5")
        )
        self._relist_stats = {"ok": 0, "failed": 0}
        # Anti-entropy reconciler (cache/antientropy.py), built lazily:
        # the periodic divergence sweep and the gap-repair relist share
        # one reconcile engine.
        self._antientropy = None

        # Bind-intent journal (doc/design/robustness.md, failover):
        # at commit-dispatch time every bind batch appends a durable
        # intent record to the cluster's journal seam BEFORE any side
        # effect is issued, and each task is marked applied/failed as
        # its bind drains — so a successor leader can classify every
        # in-flight bind after a crash. KBT_BIND_JOURNAL=0 disables.
        self.journal_enabled = (
            getattr(cluster, "supports_bind_journal", False)
            and os.environ.get("KBT_BIND_JOURNAL", "1") != "0"
        )
        # Identity stamped into journal records (the elector identity in
        # server mode, the sim instance id in drills): recovery
        # distinguishes a predecessor's intents from its own.
        self.leader_identity = f"{scheduler_name}-{os.getpid()}"

        self._executor = ThreadPoolExecutor(
            max_workers=4, thread_name_prefix="cache-sideeffect"
        )
        # Mirror bookkeeping gets its own single worker: snapshot()
        # barriers on it, so it must never queue behind a slow per-task
        # volume bind occupying the shared pool (those can block up to
        # the 30s bind timeout each).
        self._bookkeeping_executor = ThreadPoolExecutor(
            max_workers=1, thread_name_prefix="cache-bookkeeping"
        )
        self._inflight = 0
        self._bookkeeping_inflight = 0
        self._inflight_cond = threading.Condition(
            wrap_lock("cache.inflight_cond", threading.RLock())
        )
        self._synced = cluster is None
        self._stop = threading.Event()
        # Leadership fence (None = unfenced). Set by the loop watchdog /
        # leader-election layer; checked at every bind/evict dispatch
        # point, including the async side-effect halves — a side-effect
        # thread queued by a leader that has since been deposed must not
        # issue its bind against the cluster. Guarded by its OWN lock,
        # never self.mutex: the watchdog fences precisely when a wedged
        # cycle may be deadlocked HOLDING the mutex, and the fencing
        # path must not join that deadlock.
        self._fence_reason: Optional[str] = None
        # LEAF lock (lockdebug.LEAF_LOCKS + the kbtlint leaf rule):
        # nothing may be acquired while it is held.
        self._fence_lock = wrap_lock("cache.fence_lock")
        self._fence_refusals = 0

        # KBT_LOCK_DEBUG=2 write-witness (no-op otherwise): the runtime
        # twin of kbtlint's guarded-by pass, per named lock. Attribute
        # REBINDS only — item mutations of the mirror maps are covered
        # by the dirty-ledger pass + fingerprint verification.
        witness_writes(self, "cache.mutex", (
            "jobs", "nodes", "queues", "priority_classes",
            "default_priority", "default_priority_class", "_priority_gen",
            "_snap_gen", "_snap_pool", "_last_snap_jobs",
            "_last_snap_nodes", "_snap_total_allocatable", "_snap_fp",
            "_snap_fp_priority_gen", "_full_backlog_jobs",
            "_full_backlog_nodes",
        ))
        witness_writes(self, "cache.fence_lock", (
            "_fence_reason", "_fence_refusals",
        ))
        witness_writes(self, "cache.inflight_cond", (
            "_inflight", "_bookkeeping_inflight",
        ))

    # -- leadership fencing ---------------------------------------------------

    def fence(self, reason: str) -> None:
        """Refuse all future bind/evict side effects (idempotent; first
        reason wins — it names the original deposition cause)."""
        with self._fence_lock:
            if self._fence_reason is None:
                self._fence_reason = reason or "fenced"
        logger.error(
            "scheduler cache FENCED (%s): all bind/evict side effects "
            "will be refused", self._fence_reason,
        )

    def unfence(self) -> None:
        """Lift the fence (tests; a re-elected process restarts its
        cache instead — fencing is meant to be terminal)."""
        with self._fence_lock:
            self._fence_reason = None
            self._fence_refusals = 0

    def fence_reason(self) -> Optional[str]:
        return self._fence_reason

    def _refused_by_fence(self, what: str) -> bool:
        """One dispatch-point fence check; counts the refusal. Every
        refusal bumps the metric, but the log line is damped: fencing
        a leader with a deep bind backlog refuses one call per queued
        pod, and tens of thousands of identical warnings would bury
        the one FENCED line that names the deposition cause."""
        reason = self._fence_reason
        if reason is None:
            return False
        try:
            from .. import metrics

            metrics.register_bind_fenced()
        except Exception:  # pragma: no cover - metrics must never kill
            logger.exception("fence metric update failed")
        with self._fence_lock:
            self._fence_refusals += 1
            n = self._fence_refusals
        if n <= 3 or n % 1000 == 0:
            logger.warning(
                "fenced cache (%s) refused %s (%d refusals so far)",
                reason, what, n,
            )
        return True

    def _submit_side_effect(self, fn, bookkeeping: bool = False) -> None:
        """Run a bind/evict side effect on the async pool, tracking it so
        tests/benchmarks can barrier on completion (the reference's
        equivalent is draining the fake binder channel with a timeout,
        allocate_test.go:199-209). ``bookkeeping=True`` additionally
        counts the job toward the mirror-consistency barrier that
        :meth:`snapshot` takes — ONLY cache-mirror updates belong there;
        a slow per-task volume bind must never stall the next cycle."""
        with self._inflight_cond:
            self._inflight += 1
            if bookkeeping:
                self._bookkeeping_inflight += 1

        # Tracer handshake: side-effect spans adopt the submitting
        # span's id, so async binds/evicts render as worker-pool tracks
        # nested under the cycle that queued them.
        traced = TRACER.enabled
        parent = TRACER.capture() if traced else 0
        span_name = (
            "cache_bookkeeping" if bookkeeping else "cache_side_effect"
        )

        def wrapped():
            try:
                if traced:
                    with TRACER.adopt(parent), _obs_span(span_name):
                        fn()
                else:
                    fn()
            except Exception:
                # A side-effect job's Future is never read, so an
                # escaping exception would otherwise vanish — and for
                # bookkeeping jobs that means tasks already bulk-moved
                # to BINDING silently stay there. Log loudly; the
                # per-task revert/resync paths inside the job are the
                # real recovery, this is the backstop.
                logger.exception("side-effect job failed")
            finally:
                with self._inflight_cond:
                    self._inflight -= 1
                    if bookkeeping:
                        self._bookkeeping_inflight -= 1
                    self._inflight_cond.notify_all()

        (self._bookkeeping_executor if bookkeeping
         else self._executor).submit(wrapped)

    def wait_for_bookkeeping(self, timeout: float = 60.0) -> bool:
        """Block until every deferred cache-mirror update (bind_batch
        bookkeeping) has executed."""
        deadline = time.monotonic() + timeout
        with self._inflight_cond:
            while self._bookkeeping_inflight > 0:
                remaining = deadline - time.monotonic()
                if remaining <= 0:
                    return False
                self._inflight_cond.wait(remaining)
        return True

    def wait_for_side_effects(self, timeout: float = 10.0) -> bool:
        """Block until every queued async bind/evict has executed."""
        deadline = time.monotonic() + timeout
        with self._inflight_cond:
            while self._inflight > 0:
                remaining = deadline - time.monotonic()
                if remaining <= 0:
                    return False
                self._inflight_cond.wait(remaining)
        return True

    # -- watch ingest (informer analog) -------------------------------------

    def _build_dispatch(self):
        return {
            ("Pod", ADDED): self.add_pod,
            ("Pod", MODIFIED): lambda o: self.update_pod(o, o),
            ("Pod", DELETED): self.delete_pod,
            ("Node", ADDED): self.add_node,
            ("Node", MODIFIED): lambda o: self.update_node(o, o),
            ("Node", DELETED): self.delete_node,
            ("PodGroup", ADDED): self.add_pod_group,
            ("PodGroup", MODIFIED): lambda o: self.update_pod_group(o, o),
            ("PodGroup", DELETED): self.delete_pod_group,
            ("Queue", ADDED): self.add_queue,
            ("Queue", MODIFIED): lambda o: self.update_queue(o, o),
            ("Queue", DELETED): self.delete_queue,
            ("PriorityClass", ADDED): self.add_priority_class,
            ("PriorityClass", MODIFIED): lambda o: self.update_priority_class(o, o),
            ("PriorityClass", DELETED): self.delete_priority_class,
            ("PodDisruptionBudget", ADDED): self.add_pdb,
            ("PodDisruptionBudget", MODIFIED): lambda o: self.update_pdb(o, o),
            ("PodDisruptionBudget", DELETED): self.delete_pdb,
        }

    def _on_watch_event(self, kind: str, event_type: str, obj,
                        rv=None) -> None:
        if rv is None:
            self._dispatch_event(kind, event_type, obj)
            return
        # Admission and application are ATOMIC under the mutex: two
        # concurrent deliveries for the same object could otherwise be
        # admitted in rv order but applied inverted (B's DELETE rv=N+1
        # lands between A's admit of rv=N and A's apply), resurrecting
        # deleted state — exactly the regression the guard exists to
        # prevent. The mutex is re-entrant; handlers take it anyway.
        # Anomaly metrics flush AFTER the hold (no foreign locks under
        # cache.mutex).
        with self.mutex:
            admitted = self._admit_event(kind, event_type, obj, rv)
            if admitted:
                self._dispatch_event(kind, event_type, obj)
        self._flush_anomaly_metrics()

    def _dispatch_event(self, kind: str, event_type: str, obj) -> None:
        fn = self._dispatch.get((kind, event_type))
        if fn is not None:
            try:
                fn(obj)
            except Exception:  # watch handlers must not kill the dispatcher
                logger.exception(
                    "failed to handle %s %s event in cache", kind, event_type
                )

    # -- event-stream integrity guards ---------------------------------------

    # Per-object memos for objects already DELETED are pruned once the
    # stream has moved this far past the deletion — a very-late stale
    # event for a long-dead object is then applied-and-reconciled like
    # any rv-less event instead of guarded, which is safe (handlers are
    # idempotent) and keeps the memo map O(live objects).
    _WATCH_MEMO_WINDOW = 4096

    @staticmethod
    def _event_key(kind: str, obj) -> str:
        """Guard identity for one watched object. Pods key on uid (a
        recreated pod under the same name is a NEW object whose events
        must not be judged against its predecessor's versions);
        everything else keys on namespace/name like the cluster store."""
        if kind == "Pod":
            try:
                return obj.uid
            except AttributeError:
                pass
        meta = obj.metadata
        return f"{meta.namespace}/{meta.name}" if meta.namespace else meta.name

    def _note_anomaly_locked(self, kind: str, n: int = 1) -> None:
        """Count one absorbed anomaly into the state dict (caller holds
        the mutex). The Prometheus side is flushed AFTER the mutex is
        released (_flush_anomaly_metrics) — no foreign locks are taken
        under cache.mutex."""
        self._event_anomalies[kind] = (
            self._event_anomalies.get(kind, 0) + n
        )
        self._anomaly_flush.append((kind, n))

    def _flush_anomaly_metrics(self) -> None:
        # Lock-free fast path: anomalies are rare, and re-acquiring the
        # mutex on EVERY admitted event just to find the flush list
        # empty would double ingest-path mutex traffic. A benignly
        # stale non-empty miss only defers the flush to the next event
        # or checkpoint (appends happen under the mutex).
        if not self._anomaly_flush:
            return
        with self.mutex:
            pending, self._anomaly_flush = self._anomaly_flush, []
        if not pending:
            return
        try:
            from .. import metrics

            for kind, n in pending:
                metrics.register_event_anomaly(kind, n)
        except Exception:  # pragma: no cover - metrics must never kill
            logger.exception("event anomaly metric update failed")

    def _admit_event(self, kind: str, event_type: str, obj,
                     rv) -> bool:
        """Ordering/duplicate/gap guard for one watch delivery. Returns
        False when the event must be ABSORBED (duplicate or stale —
        applying it would regress mirror state that a newer event
        already wrote). Only integer rvs engage the guards; KubeCluster
        delivers opaque string rvs and relies on its own relist
        machinery."""
        if not isinstance(rv, int) or rv <= 0:
            return True
        key = (kind, self._event_key(kind, obj))
        admit = True
        with self.mutex:
            # Stream-level contiguity: every write bumps the cluster's
            # event rv by exactly one, so a hole that persists across
            # drain checkpoints is a DROPPED event (watch gap).
            if rv > self._stream_max_rv:
                if (
                    self._stream_baselined
                    and rv > self._stream_max_rv + 1
                ):
                    self._stream_missing.update(
                        range(self._stream_max_rv + 1, rv)
                    )
                    if len(self._stream_missing) > self._WATCH_MEMO_WINDOW:
                        # Pathological hole: stop tracking individual
                        # rvs and go straight to a full relist.
                        self._note_anomaly_locked("gap")
                        self._stream_missing.clear()
                        self._stream_missing_prev.clear()
                        self._relist_pending = True
                self._stream_max_rv = rv
                self._stream_baselined = True
            elif rv in self._stream_missing:
                # Late arrival of an out-of-order event: the hole was
                # delivery reordering, not loss — absorb the anomaly
                # count and fill the hole.
                self._stream_missing.discard(rv)
                self._stream_missing_prev.discard(rv)
                self._note_anomaly_locked("reorder")
            # Per-object ordering: a duplicate (same rv) or stale
            # (older rv) delivery is skipped — the mirror already
            # reflects the same-or-newer state for this object.
            last = self._watch_rv.get(key)
            if last is not None and rv <= last:
                self._note_anomaly_locked(
                    "duplicate" if rv == last else "stale"
                )
                admit = False
            if admit:
                self._watch_rv[key] = rv
                if event_type == DELETED:
                    self._watch_deleted.append((rv, key))
                while (
                    self._watch_deleted
                    and self._watch_deleted[0][0]
                    < self._stream_max_rv - self._WATCH_MEMO_WINDOW
                ):
                    old_rv, old_key = self._watch_deleted.popleft()
                    # Only drop the memo if no NEWER object recycled
                    # the key (a flapped node re-added by name).
                    if self._watch_rv.get(old_key, -1) <= old_rv:
                        self._watch_rv.pop(old_key, None)
        # NOTE: no metric flush here — the caller (_on_watch_event)
        # flushes after releasing its outer mutex hold.
        return admit

    def _adopt_listed_rv(self, kind: str, obj) -> None:
        """After a list/relist applied this object's state, pin its
        guard memo to the listed resourceVersion so late stale events
        predating the list are absorbed, not re-applied."""
        rv = getattr(obj.metadata, "resource_version", 0)
        if isinstance(rv, int) and rv > 0:
            with self.mutex:
                key = (kind, self._event_key(kind, obj))
                if self._watch_rv.get(key, 0) < rv:
                    self._watch_rv[key] = rv

    def _check_watch_gap(self) -> bool:
        """Gap-confirmation checkpoint, called at the deterministic
        drain points (drain_resync_queue; the background resync loop's
        idle beat in production). A missing rv seen at TWO consecutive
        checkpoints is a confirmed drop (in-flight reordering resolves
        within one); confirmation queues a relist, and the relist runs
        here — rate-limited — through the same drain seam. Returns True
        when integrity state changed (the settle loop's quiescence
        signal)."""
        if self.cluster is None:
            return False
        with self.mutex:
            confirmed = self._stream_missing & self._stream_missing_prev
            progressed = bool(
                self._stream_missing ^ self._stream_missing_prev
            )
            self._stream_missing_prev = set(self._stream_missing)
            if confirmed:
                self._note_anomaly_locked("gap", len(confirmed))
                self._stream_missing -= confirmed
                self._stream_missing_prev -= confirmed
                self._relist_pending = True
            pending = self._relist_pending
        self._flush_anomaly_metrics()
        relisted = self._maybe_relist() if pending else False
        return relisted or bool(confirmed) or progressed

    def _maybe_relist(self) -> bool:
        """Run the gap-repair relist unless rate-limited (at most one
        per KBT_RELIST_MIN_INTERVAL on the injectable relist clock —
        a relist is an O(cluster) read and a storm of gaps must not
        turn into a storm of lists). While rate-limited the gap stays
        pending: the periodic anti-entropy sweep repairs the affected
        objects meanwhile, and the next eligible checkpoint relists."""
        now = self._relist_clock()
        with self.mutex:
            if (
                self._relist_last is not None
                and now - self._relist_last < self._relist_min_interval
            ):
                return False
            self._relist_last = now
        ok = False
        try:
            report = self.antientropy.full_reconcile()
            ok = report is not None
        except Exception:
            logger.exception("watch-gap relist failed; gap stays pending")
        with self.mutex:
            self._relist_stats["ok" if ok else "failed"] += 1
            if ok:
                self._relist_pending = False
                # The reconcile IS the stream state now: holes predating
                # it are repaired by construction.
                self._stream_missing.clear()
                self._stream_missing_prev.clear()
                cur = getattr(
                    self.cluster, "current_resource_version", None
                )
                if cur is not None:
                    try:
                        self._stream_max_rv = max(
                            self._stream_max_rv, int(cur())
                        )
                    except Exception:  # pragma: no cover - defensive
                        logger.exception("relist stream-rv adoption failed")
        try:
            from .. import metrics

            metrics.register_relist("ok" if ok else "failed")
        except Exception:  # pragma: no cover - metrics must never kill
            logger.exception("relist metric update failed")
        return True

    @property
    def antientropy(self) -> object:
        """The cluster-truth reconciler (cache/antientropy.py), shared
        by the periodic divergence sweep and the gap-repair relist.
        Constructed under the mutex: the first relist (resync thread)
        and the first periodic sweep (scheduler thread) can race here,
        and two engines would split the divergence counters."""
        if self._antientropy is None:
            from .antientropy import AntiEntropy

            with self.mutex:
                if self._antientropy is None:
                    self._antientropy = AntiEntropy(self)
        return self._antientropy

    def run_antientropy_if_due(self) -> Optional[dict]:
        """Scheduler hook: run the periodic anti-entropy sweep when its
        cadence says so (see AntiEntropy.sweep_if_due)."""
        if self.cluster is None:
            return None
        try:
            return self.antientropy.sweep_if_due()
        except Exception:  # the sweep must never fail a cycle
            logger.exception("anti-entropy sweep failed")
            return None

    def integrity_state(self) -> dict:
        """One JSON-friendly blob for /debug/vars and the sim report:
        absorbed event anomalies, gap/relist state, and the anti-entropy
        divergence counters."""
        with self.mutex:
            state = {
                "event_anomalies": dict(
                    sorted(self._event_anomalies.items())
                ),
                "stream_max_rv": self._stream_max_rv,
                "stream_missing": len(self._stream_missing),
                "relist_pending": self._relist_pending,
                "relists": dict(self._relist_stats),
            }
        ae = self._antientropy
        if ae is not None:
            state.update(ae.state_dict())
        else:
            state.update({
                "divergence_detected": {},
                "divergence_repaired": {},
                "sweeps": 0,
            })
        return state

    def start_ingest(self) -> None:
        """Attach the cluster watch and replay the initial object list
        (the informer-start half of :meth:`run`), WITHOUT starting the
        background resync/cleanup loops. The simulator uses this
        directly: it drains the retry queues itself at deterministic
        barrier points (:meth:`drain_resync_queue` /
        :meth:`drain_cleanup_queue`), so no free-running thread may
        race its virtual clock."""
        if self.cluster is not None:
            # Watch BEFORE the initial list so objects created during the list
            # are not lost; duplicate ADDs are tolerated (handlers key by uid).
            self.cluster.add_watch(self._on_watch_event)
            for kind in (
                "Node",
                "Queue",
                "PriorityClass",
                "PodGroup",
                "PodDisruptionBudget",
                "Pod",
            ):
                for obj in self.cluster.list_objects(kind):
                    self._on_watch_event(kind, ADDED, obj)
                    # Pin the guard memos to the listed versions so a
                    # late stale event predating the list is absorbed.
                    self._adopt_listed_rv(kind, obj)
            # The list is the stream position now: gap tracking starts
            # from the cluster's current event rv, not from whatever
            # watch event happens to arrive first.
            cur = getattr(self.cluster, "current_resource_version", None)
            if cur is not None:
                try:
                    with self.mutex:
                        self._stream_max_rv = max(
                            self._stream_max_rv, int(cur())
                        )
                        self._stream_baselined = True
                except Exception:  # pragma: no cover - defensive
                    logger.exception("initial stream-rv adoption failed")
            self._synced = True

    def run(self, stop_event: Optional[threading.Event] = None) -> None:
        """Start ingest + resync/cleanup loops (reference cache.go:355-377)."""
        self._stop = stop_event or threading.Event()
        self.start_ingest()
        threading.Thread(
            target=self._process_resync_loop, daemon=True, name="cache-resync"
        ).start()
        threading.Thread(
            target=self._process_cleanup_loop, daemon=True, name="cache-cleanup"
        ).start()

    def wait_for_cache_sync(self, stop_event=None, timeout: float = 10.0) -> bool:
        deadline = time.time() + timeout
        while not self._synced and time.time() < deadline:
            time.sleep(0.01)
        return self._synced

    # -- retry loops --------------------------------------------------------

    def _retry_delay(self, attempt: int) -> float:
        return min(self._base_retry_delay * (2**attempt), self._max_retry_delay)

    def _resync_task(self, task: TaskInfo, attempt: int = 0) -> None:
        """reference cache.go:588-595 (AddRateLimited analog) — with a
        terminal cap: past ``KBT_RESYNC_MAX_ATTEMPTS`` the task is
        dropped (``task_resync_terminal_total``) and named in its job's
        unschedulable verdict so ``explain``/`/debug/jobs` answer "where
        did that pod go"."""
        if attempt >= self._max_resync_attempts:
            self._drop_poisoned_task(task, attempt)
            return
        self.err_tasks.put((task, attempt))

    def _drop_poisoned_task(self, task: TaskInfo, attempt: int) -> None:
        logger.error(
            "task %s/%s dropped from resync after %d failed reconcile "
            "attempts (poisoned; will not be retried — external pod "
            "events re-admit it)",
            task.namespace, task.name, attempt,
        )
        try:
            from .. import metrics

            metrics.register_resync_terminal()
        except Exception:  # pragma: no cover - metrics must never kill
            logger.exception("resync-terminal metric update failed")
        try:
            from ..obs import explain

            with self.mutex:
                job = self.jobs.get(task.job)
                job_name = job.name if job is not None else task.name
            explain.note_resync_terminal(
                task.job, task.namespace, job_name,
                f"{task.namespace}/{task.name}", attempt,
            )
        except Exception:  # pragma: no cover - forensics only
            logger.exception("resync-terminal verdict note failed")

    def _queue_job_cleanup(self, job: JobInfo, attempt: int = 0) -> None:
        self.deleted_jobs.put((job, attempt))

    def _process_resync_loop(self) -> None:
        while not self._stop.is_set():
            try:
                task, attempt = self.err_tasks.get(timeout=0.2)
            except queue.Empty:
                # Idle beat: the watch-gap checkpoint (and its
                # rate-limited relist) runs here in production — the
                # same seam the simulator drives via drain_resync_queue.
                try:
                    self._check_watch_gap()
                except Exception:
                    logger.exception("watch-gap checkpoint failed")
                continue
            try:
                self._sync_task(task)
            except Exception:
                logger.exception("failed to resync task %s/%s", task.namespace, task.name)
                self._stop.wait(self._retry_delay(attempt))
                self._resync_task(task, attempt + 1)

    def drain_resync_queue(self) -> int:
        """Synchronously reconcile every queued failed-side-effect task,
        in sorted order (queue arrival order depends on worker-thread
        timing; sorting makes the drain — and therefore a simulated
        cycle's end state — deterministic). Returns the amount of work
        done (synced tasks, plus one when the watch-gap checkpoint made
        progress — callers loop this drain to quiescence, and a pending
        gap confirmation or relist IS unfinished work). The background
        resync loop and this drain are mutually exclusive by
        construction: the loop only runs when :meth:`run` started it,
        the drain is for callers that used :meth:`start_ingest`."""
        # Watch-gap checkpoint first: a confirmed gap's relist repairs
        # the mirror BEFORE stale tasks are reconciled against it.
        gap_work = False
        try:
            gap_work = self._check_watch_gap()
        except Exception:
            logger.exception("watch-gap checkpoint failed during drain")
        tasks = []
        while True:
            try:
                tasks.append(self.err_tasks.get_nowait())
            except queue.Empty:
                break
        tasks.sort(key=lambda item: (
            item[0].namespace, item[0].name, item[0].uid
        ))
        synced = 0
        for task, attempt in tasks:
            try:
                self._sync_task(task)
                synced += 1
            except Exception:
                # Mirror the background loop's retry contract: a failed
                # reconcile goes back on the queue (attempt+1) for the
                # next drain instead of silently dropping the task into
                # permanent staleness. Only SUCCESSFUL syncs count
                # toward the return value, so a poisoned task cannot
                # spin the caller's drain-until-quiescent loop.
                logger.exception(
                    "failed to resync task %s/%s during drain; requeued",
                    task.namespace, task.name,
                )
                self._resync_task(task, attempt + 1)
        return synced + (1 if gap_work else 0)

    def drain_cleanup_queue(self) -> int:
        """Synchronously process the deleted-job queue once: terminated
        jobs are removed from the mirror, the rest are re-queued (the
        loop form waits with backoff; the drain leaves them for the next
        barrier). Returns the number of jobs actually removed."""
        jobs = []
        while True:
            try:
                jobs.append(self.deleted_jobs.get_nowait())
            except queue.Empty:
                break
        removed = 0
        for job, attempt in sorted(
            jobs, key=lambda item: item[0].uid
        ):
            with self.mutex:
                terminated = job_terminated(job)
                if terminated:
                    self.jobs.pop(job.uid, None)
                    # Removal must reach the incremental snapshot's
                    # delta set or the stale entry outlives the job.
                    self._stamp_dirty(job.uid)
                    removed += 1
            if terminated:
                self._forget_job_metrics(job)
            else:
                self._queue_job_cleanup(job, attempt + 1)
        return removed

    def _process_cleanup_loop(self) -> None:
        """reference cache.go:556-585 (waits for JobTerminated)"""
        while not self._stop.is_set():
            try:
                job, attempt = self.deleted_jobs.get(timeout=0.2)
            except queue.Empty:
                continue
            with self.mutex:
                terminated = job_terminated(job)
                if terminated:
                    self.jobs.pop(job.uid, None)
                    self._stamp_dirty(job.uid)
            if terminated:
                self._forget_job_metrics(job)
            else:
                self._stop.wait(self._retry_delay(attempt))
                self._queue_job_cleanup(job, attempt + 1)

    @staticmethod
    def _forget_job_metrics(job: JobInfo) -> None:
        """Label-set GC: a removed job's per-job metric series
        (``unschedule_task_count`` / ``job_retry_counts``, keyed on the
        pod-group name the gang plugin labels with) must leave the
        registry with it — an unbounded-cardinality leak otherwise.
        The placement-latency ledger's per-pod entries GC on the same
        hook (the PR 6 pattern: per-subject observability state dies
        with the subject)."""
        try:
            from .. import metrics

            metrics.forget_job(job.name)
        except Exception:  # pragma: no cover - metrics must never kill
            logger.exception("job metric label GC failed")
        try:
            from ..obs.latency import LEDGER

            LEDGER.forget_job(job.uid)
        except Exception:  # pragma: no cover - forensics only
            logger.exception("latency ledger job GC failed")

    # -- snapshot (reference cache.go:612-659) --------------------------------

    def snapshot(self, micro: bool = False) -> ClusterInfo:
        """Deep-clone the schedulable world — with a copy-on-write pool.

        ``micro=True`` marks a micro-cycle snapshot: the incremental
        path may verify only the ledger-named positions (plus the
        appended arrival tail) instead of the full O(n) fingerprint
        compare — see _snapshot_incremental. Periodic snapshots always
        run the full verification and remain the reconciliation
        authority for any out-of-band mutation the ledgers missed.

        The reference re-clones everything each 1 Hz cycle
        (cache.go:612-659); at 50k tasks that alone busts the cycle
        budget (SURVEY §7 hard part (e)). Here each clone is cached and
        REUSED while (a) its source object hasn't changed — every
        JobInfo/NodeInfo mutator bumps ``_ver`` — and (b) the clone
        itself wasn't mutated by the session it was handed to (session
        allocate/pipeline/evict bump the clone's ``_ver``). Either bump
        forces a fresh clone, so cache state can never leak into or out
        of a session. Consequence of reuse: clones are shared between
        CONSECUTIVE snapshots when nothing changed in between — valid
        because a snapshot's objects are only ever mutated by its own
        session, and the scheduler runs sessions strictly one at a time
        (reference semantics: one runOnce per cycle, scheduler.go:84)."""
        # Barrier on deferred bind bookkeeping (bind_batch runs the
        # mirror update on the side-effect pool): a snapshot taken with
        # a half-applied batch would re-place already-bound tasks. In
        # the 1 Hz steady state the batch finished long ago and this is
        # a no-op; a timeout degrades to the reference's behavior —
        # schedule on the freshest mirror available and let resync
        # reconcile. Deliberately NOT wait_for_side_effects: a slow
        # per-task volume bind must not stall the next cycle.
        if not self.wait_for_bookkeeping(timeout=60.0):
            logger.warning(
                "bind bookkeeping still in flight after 60s; snapshotting "
                "the current mirror state"
            )
        with self.mutex:
            snap = ClusterInfo()
            if (
                self._snap_fp is not None
                and self._snap_fp_priority_gen == self._priority_gen
                and os.environ.get("KBT_SNAPSHOT_INCREMENTAL", "1") != "0"
            ):
                self._snapshot_incremental(snap, micro=micro)
            else:
                self._snapshot_full(snap)
            for name, q in self.queues.items():
                snap.queues[name] = q.clone()
            self._snap_gen += 1
            snap.snap_gen = self._snap_gen
            total = self._snap_total_allocatable
            snap.total_allocatable = (
                total.clone() if total is not None else None
            )
            # Fold this interval's full-dirty names into the backlog;
            # report the WHOLE backlog (names stay full-dirty until a
            # refresh absorbs them — see note_full_absorbed). A name
            # that ALSO saw a third-party event, now or in any
            # un-absorbed interval, stays conservatively full-dirty.
            self._full_backlog_jobs |= self._dirty_jobs
            self._full_backlog_nodes |= self._dirty_nodes
            snap.dirty_jobs = frozenset(self._full_backlog_jobs)
            snap.dirty_nodes = frozenset(self._full_backlog_nodes)
            snap.dirty_jobs_narrow = frozenset(
                self._dirty_jobs_alloc - self._full_backlog_jobs
            )
            snap.dirty_nodes_narrow = frozenset(
                self._dirty_nodes_alloc - self._full_backlog_nodes
            )
            self._dirty_jobs.clear()
            self._dirty_nodes.clear()
            self._dirty_jobs_alloc.clear()
            self._dirty_nodes_alloc.clear()
            self._touched_clone_jobs.clear()
            self._touched_clone_nodes.clear()
            return snap

    def note_clones_touched(
        self, job_uids: Iterable[str], node_names: Iterable[str]
    ) -> None:
        """A closing session reports the snapshot clones whose ``_ver``
        it bumped (allocate/pipeline/evict/dispatch and Statement ops).
        The micro fast-verification rechecks exactly these positions;
        without the report every clone would need the O(n) ``_ver``
        listcomp compare that dominates the warm-noop open floor."""
        with self.mutex:
            self._touched_clone_jobs.update(job_uids)
            self._touched_clone_nodes.update(node_names)

    def note_full_absorbed(self, job_keys, node_names) -> None:
        """A tensorize refresh ran against a session carrying these
        full-dirty names: drop them from the backlog (called by
        solver/snapshot._store_refresh_stats). Names stamped since that
        session's snapshot live in the live ledger, not the backlog, so
        this never forgets fresh churn."""
        with self.mutex:
            self._full_backlog_jobs.difference_update(job_keys)
            self._full_backlog_nodes.difference_update(node_names)

    def _job_priority(self, job: JobInfo) -> None:
        """Resolve job priority from the class map (cache.go:641-650)."""
        if self.enable_priority_class and job.pod_group is not None:
            job.priority = self.default_priority
            pc = self.priority_classes.get(
                job.pod_group.spec.priority_class_name
            )
            if pc is not None:
                job.priority = pc.value

    def _snapshot_full(self, snap: ClusterInfo) -> None:
        """The reference-shaped pool walk: touch every mirror object,
        re-cloning any whose source or clone fingerprint moved. Also
        (re)establishes the incremental baseline: the last-snapshot
        dicts, the ready-node allocatable running sum, and the
        verification fingerprint."""
        from ..api import Resource

        pool_jobs: Dict[str, tuple] = {}
        pool_nodes: Dict[str, tuple] = {}
        old_jobs, old_nodes = self._snap_pool
        total = Resource.empty()
        for name, node in self.nodes.items():
            if not node.ready():
                continue
            entry = old_nodes.get(name)
            if (
                entry is not None
                and entry[0] == node._ver
                and entry[2] == entry[1]._ver
            ):
                pool_nodes[name] = entry
            else:
                entry = pool_nodes[name] = _pool_entry(node)
            snap.nodes[name] = entry[1]
            total.add(entry[1].allocatable)
        for key, job in self.jobs.items():
            # Jobs without a scheduling spec (neither PodGroup nor the
            # legacy PDB source) are not schedulable
            # (reference cache.go:634-640).
            if job.pod_group is None and job.pdb is None:
                continue
            self._job_priority(job)
            entry = old_jobs.get(key)
            if (
                entry is not None
                and entry[0] == job._ver
                and entry[2] == entry[1]._ver
                and entry[1].priority == job.priority
            ):
                pool_jobs[key] = entry
            else:
                entry = pool_jobs[key] = _pool_entry(job)
            snap.jobs[key] = entry[1]
        # Entries for deleted objects fall away with the pool swap.
        self._snap_pool = (pool_jobs, pool_nodes)
        self._last_snap_jobs = dict(snap.jobs)
        self._last_snap_nodes = dict(snap.nodes)
        self._snap_total_allocatable = total
        self._refresh_snap_fingerprint()

    def _refresh_snap_fingerprint(self) -> None:
        """Rebuild the aligned verification lists over the CURRENT
        mirror + pool state (called after every full walk). Object
        references are pinned in the lists — identity compares against
        them are exact witnesses (a pinned object's id can never be
        recycled under a new object)."""

        def fp(mirror: dict, pool: dict):
            names = list(mirror.keys())
            objs = list(mirror.values())
            vers = [o._ver for o in objs]
            entries = [pool.get(name) for name in names]
            clone_vers = [
                e[1]._ver if e is not None else -1 for e in entries
            ]
            return [names, objs, vers, entries, clone_vers]

        pool_jobs, pool_nodes = self._snap_pool
        self._snap_fp = (
            fp(self.jobs, pool_jobs), fp(self.nodes, pool_nodes)
        )
        self._snap_fp_priority_gen = self._priority_gen
        # Position maps are rebuilt lazily on the next micro snapshot
        # (an eager rebuild would tax every full walk even when no
        # micro cycle ever consumes it).
        self._snap_fp_index = [None, None]

    def _snapshot_incremental(self, snap: ClusterInfo, micro: bool = False) -> None:
        """O(churn) pool update behind an exact O(n)-cheap verification:
        C-level list compares of per-object (identity, _ver) and
        per-pool-entry (identity via pinned reference, clone _ver)
        against the previous snapshot's fingerprint find EXACTLY the
        names whose mirror object or session clone moved — no trust in
        the dirty ledger or any caller-side reporting, so a test poking
        objects directly is caught like any watch event. Only those
        names re-run the pool walk body; everything else reuses its
        entry untouched. Key APPENDS (new pods/jobs/nodes) extend the
        fingerprint in place; a deletion or reorder falls back to the
        full walk, as does any priority-class change.
        KBT_SNAPSHOT_INCREMENTAL=0 forces the full walk every cycle.

        MICRO snapshots (``micro=True``, default-on via
        KBT_MICRO_VERIFY=ledger) skip the two O(n) Python-level ``_ver``
        listcomps — the dominant term of the warm-noop open floor at
        scale — and verify only (a) the positions named by the dirty
        ledgers (watch events + bind/evict bookkeeping, whose
        completeness kbtlint's dirty-ledger pass enforces) and the
        session clone-touch reports (note_clones_touched), plus (b) the
        appended arrival tail. A deletion named by the ledger still
        falls back to the full walk. Out-of-band pokes that bypass every
        ledger (nothing in-tree does) are caught at the next PERIODIC
        snapshot, which always runs the full compare — the periodic
        cycle stays the reconciliation authority. KBT_MICRO_VERIFY=full
        pins the pre-r17 behavior: full verification on every snapshot."""
        job_fp, node_fp = self._snap_fp
        pool_jobs, pool_nodes = self._snap_pool

        def dirty_positions(fp, mirror, pool):
            names, objs, vers, entries, clone_vers = fp
            n = len(names)
            if len(mirror) < n:
                return None  # deletion: full walk
            cur_objs = list(mirror.values())
            appended = []
            if len(cur_objs) > n:
                # Python dicts append new keys at the end; if the first
                # n entries are untouched, the tail is pure arrival.
                cur_names = list(mirror.keys())
                if cur_names[:n] != names:
                    return None
                appended = list(range(n, len(cur_names)))
                names.extend(cur_names[n:])
                objs.extend(cur_objs[n:])
                vers.extend(o._ver for o in cur_objs[n:])
                entries.extend([None] * len(appended))
                clone_vers.extend([-1] * len(appended))
                cur_objs = cur_objs[:n]
            head_objs = objs[:n] if appended else objs
            idxs = []
            if not (cur_objs == head_objs
                    and vers[:n] == [o._ver for o in cur_objs]):
                idxs = [
                    i for i, o in enumerate(cur_objs)
                    if head_objs[i] is not o or vers[i] != o._ver
                ]
                if list(mirror.keys())[:n] != names[:n]:
                    return None  # replacement/reorder: full walk
                for i in idxs:
                    objs[i] = cur_objs[i]
                    vers[i] = cur_objs[i]._ver
            # Session clones mutate without touching the mirror object:
            # the pinned entry references read the CURRENT clone _ver.
            if clone_vers[:n] != [
                e[1]._ver if e is not None else -1 for e in entries[:n]
            ]:
                seen = set(idxs)
                for i in range(n):
                    e = entries[i]
                    cv = e[1]._ver if e is not None else -1
                    if cv != clone_vers[i] and i not in seen:
                        idxs.append(i)
            return sorted(idxs) + appended

        def dirty_positions_ledger(
            fp: tuple, which: int, mirror: dict,
            ledger: Iterable[str],
        ) -> Optional[List[int]]:
            names, objs, vers, entries, clone_vers = fp
            n = len(names)
            m = len(mirror)
            if m < n:
                return None  # deletion: full walk
            index = self._snap_fp_index[which]
            if index is None or len(index) != n:
                # First micro after a refresh / slow-path append: one
                # O(n) dict build, amortized over the micro burst.
                index = {nm: i for i, nm in enumerate(names)}
                self._snap_fp_index[which] = index
            appended = []
            if m > n:
                cur_names = list(mirror.keys())
                if cur_names[:n] != names:
                    return None  # replacement/reorder: full walk
                cur_objs = list(mirror.values())
                appended = list(range(n, m))
                names.extend(cur_names[n:])
                objs.extend(cur_objs[n:])
                vers.extend(o._ver for o in cur_objs[n:])
                entries.extend([None] * len(appended))
                clone_vers.extend([-1] * len(appended))
                for i in appended:
                    index[names[i]] = i
            hit = set()
            # sorted: the walk order decides nothing (hit is a set,
            # emitted sorted) but keeps record/replay traces byte-equal.
            for nm in sorted(ledger):
                pos = index.get(nm)
                if pos is None or pos >= n:
                    continue  # arrival (tail-covered) or came-and-went
                o = mirror.get(nm)
                if o is None:
                    return None  # ledger-named deletion: full walk
                if objs[pos] is not o or vers[pos] != o._ver:
                    objs[pos] = o
                    vers[pos] = o._ver
                    hit.add(pos)
                    continue
                e = entries[pos]
                cv = e[1]._ver if e is not None else -1
                if cv != clone_vers[pos]:
                    hit.add(pos)
            return sorted(hit) + appended

        fast = micro and os.environ.get(
            "KBT_MICRO_VERIFY", "ledger"
        ) != "full"
        if fast:
            node_idxs = dirty_positions_ledger(
                node_fp, 1, self.nodes,
                self._dirty_nodes | self._dirty_nodes_alloc
                | self._touched_clone_nodes,
            )
            job_idxs = dirty_positions_ledger(
                job_fp, 0, self.jobs,
                self._dirty_jobs | self._dirty_jobs_alloc
                | self._touched_clone_jobs,
            )
            if node_idxs is not None and job_idxs is not None:
                self.snap_ledger_verifies += 1
        else:
            node_idxs = dirty_positions(node_fp, self.nodes, pool_nodes)
            job_idxs = dirty_positions(job_fp, self.jobs, pool_jobs)
            self.snap_full_verifies += 1
        if node_idxs is None or job_idxs is None:
            self._snapshot_full(snap)
            return
        dirty_node_names = [node_fp[0][i] for i in node_idxs]
        dirty_job_keys = [job_fp[0][i] for i in job_idxs]

        nodes_out = self._last_snap_nodes
        jobs_out = self._last_snap_jobs
        total = self._snap_total_allocatable
        for pos, name in zip(node_idxs, dirty_node_names):
            # In-place assignment (never pop+reinsert for a live name):
            # dict position IS the snapshot row order the tensorize
            # caches key on — reordering would read as node-set churn.
            prev = nodes_out.get(name)
            if prev is not None:
                total.sub(prev.allocatable)
            node = self.nodes[name]
            if not node.ready():
                nodes_out.pop(name, None)
                pool_nodes.pop(name, None)
                self._fp_patch(node_fp, pos, None)
                continue
            entry = pool_nodes.get(name)
            if not (
                entry is not None
                and entry[0] == node._ver
                and entry[2] == entry[1]._ver
            ):
                entry = pool_nodes[name] = _pool_entry(node)
            nodes_out[name] = entry[1]
            total.add(entry[1].allocatable)
            self._fp_patch(node_fp, pos, entry)

        for pos, key in zip(job_idxs, dirty_job_keys):
            job = self.jobs[key]
            if job.pod_group is None and job.pdb is None:
                pool_jobs.pop(key, None)
                jobs_out.pop(key, None)
                self._fp_patch(job_fp, pos, None)
                continue
            self._job_priority(job)
            entry = pool_jobs.get(key)
            if not (
                entry is not None
                and entry[0] == job._ver
                and entry[2] == entry[1]._ver
                and entry[1].priority == job.priority
            ):
                entry = pool_jobs[key] = _pool_entry(job)
            jobs_out[key] = entry[1]
            self._fp_patch(job_fp, pos, entry)

        # Hand out copies: sessions mutate their dicts (_validate_jobs
        # deletes invalid jobs; _close rebinds but tests may poke).
        snap.jobs = dict(jobs_out)
        snap.nodes = dict(nodes_out)
        snap.incremental = True

    @staticmethod
    def _fp_patch(fp, pos: int, entry) -> None:
        """Re-point one verification-fingerprint position at the pool
        entry the walk just (re)minted — the mirror-side lists were
        already adopted during verification."""
        fp[3][pos] = entry
        fp[4][pos] = entry[2] if entry is not None else -1

    # -- event-driven micro-cycles ------------------------------------------

    def set_arrival_listener(self, listener) -> None:
        """Install ``listener()`` fired (outside the mutex) whenever a
        pending pod of this scheduler lands in the mirror — the
        micro-cycle wake-up signal (scheduler.run_micro)."""
        self._arrival_listener = listener

    def _notify_arrival(self) -> None:
        listener = self._arrival_listener
        if listener is not None:
            try:
                listener()
            except Exception:  # pragma: no cover - listener is advisory
                logger.exception("arrival listener failed")

    # -- bind-intent journal --------------------------------------------------

    def _journal_append(self, task_infos) -> Optional[int]:
        """Append one intent record covering ``task_infos`` (each with
        node_name set) to the cluster journal; returns the seq, or None
        when journaling is off or the append failed. A failed append is
        LOGGED and the binds proceed — availability beats perfect
        recoverability; the resync path still covers the tasks."""
        if not self.journal_enabled or not task_infos:
            return None
        tasks = []
        gang_jobs = set()
        for ti in task_infos:
            tasks.append({
                "uid": ti.uid,
                "pod": f"{ti.namespace}/{ti.name}",
                "node": ti.node_name,
                "job": ti.job,
            })
            gang_jobs.add(ti.job)
        gangs = {}
        with self.mutex:
            for job_key in sorted(gang_jobs):
                job = self.jobs.get(job_key)
                if job is not None and job.min_available > 1:
                    gangs[job_key] = job.min_available
        record = {
            "leader": self.leader_identity,
            "tasks": tasks,
            "gangs": gangs,
        }
        try:
            seq = self.cluster.append_bind_intent(record)
        except Exception:
            logger.exception(
                "bind-intent journal append failed for %d task(s); "
                "binds proceed unjournaled", len(tasks),
            )
            return None
        try:
            from .. import metrics

            metrics.register_journal_event("appended")
        except Exception:  # pragma: no cover - metrics must never kill
            logger.exception("journal metric update failed")
        return seq

    def _journal_mark(self, seq: Optional[int], task_uid: str,
                      outcome: str) -> None:
        """Mark one task's intent outcome (applied/failed); best-effort
        — an unmarked intent classifies via cluster truth at recovery."""
        if seq is not None:
            self._journal_mark_many(seq, {task_uid: outcome})

    def _journal_mark_many(self, seq: Optional[int], marks) -> None:
        """Batched mark flush for one drained bind chunk: ONE journal
        round trip (on a real cluster, one Lease CAS) instead of one
        per task. Best-effort like the single form."""
        if seq is None or not marks:
            return
        try:
            resolved = self.cluster.mark_bind_intents(seq, marks)
        except Exception:
            logger.exception(
                "bind-intent mark flush failed for %d task(s)", len(marks)
            )
            return
        try:
            from .. import metrics

            for outcome in sorted(marks.values()):
                metrics.register_journal_event(outcome)
            if resolved:
                metrics.register_journal_event("resolved")
        except Exception:  # pragma: no cover - metrics must never kill
            logger.exception("journal metric update failed")

    # -- side effects --------------------------------------------------------

    def _find_job_and_task(self, ti: TaskInfo):
        """reference cache.go:397-419"""
        job = self.jobs.get(ti.job)
        if job is None:
            raise KeyError(f"failed to find job <{ti.job}>")
        task = job.tasks.get(ti.uid)
        if task is None:
            raise KeyError(f"failed to find task <{ti.namespace}/{ti.name}>")
        return job, task

    def _bind_bookkeeping(self, task_info: TaskInfo, hostname: str,
                          add_to_node: bool = True,
                          update_status: bool = True):
        """Under-mutex half of bind: validate, move to Binding, and (by
        default) account on the node. Returns ``(job, task, prior)``
        where ``task`` is the STORED task and ``prior`` its
        (status, node_name) before the move — what a caller must restore
        to revert a bind the node later rejects. Caller must hold
        self.mutex. ``add_to_node=False`` defers the node accounting to
        the caller (bind_batch groups it per node);
        ``update_status=False`` defers the status-index move too (the
        caller bulk-moves per job) — node_name is still set here."""
        job, task = self._find_job_and_task(task_info)
        node = self.nodes.get(hostname)
        if node is None:
            raise KeyError(
                f"failed to bind Task {task.uid} to host {hostname}: "
                f"host does not exist"
            )
        # NARROW stamp: a bind applies exactly the deltas the scheduler
        # itself computed (idle/used/count on the node, a status-index
        # move on the job) — the delta-aware tensorize patches those
        # columns instead of rebuilding the row (solver/snapshot.py).
        self._stamp_dirty_alloc(task_info.job, hostname)
        if task.status not in (TaskStatus.PENDING, TaskStatus.ALLOCATED):
            raise ValueError(
                f"failed to bind Task {task.uid}: status is "
                f"{task.status.name}, expected Pending/Allocated"
            )
        prior = (task.status, task.node_name)
        if update_status:
            job.update_task_status(task, TaskStatus.BINDING)
        task.node_name = hostname
        if add_to_node:
            node.add_task(task)
        return job, task, prior

    def _bind_side_effect(self, pod, hostname, task_snapshot,
                          journal_seq: Optional[int] = None,
                          mark_sink=None) -> None:
        """Async half of bind. The volume bind wait (up to the reference's
        30s, cache.go:260-268) runs HERE on the side-effect pool, not in
        the scheduling loop — one slow volume must not stall every other
        job's cycle. A timeout/failure releases the claim assumptions and
        resyncs the task without binding the pod.

        ``mark_sink``: chunked callers pass a dict collecting this
        task's journal outcome; the chunk flushes them in ONE journal
        round trip (_journal_mark_many) instead of one per task."""
        if self._refused_by_fence(
            f"bind side effect {pod.namespace}/{pod.name} -> {hostname}"
        ):
            # No resync either: the task is the NEW leader's to place —
            # and no journal mark: the intent stays open for the
            # successor's recovery pass to classify against cluster
            # truth (a fenced leader cannot know what landed).
            return
        try:
            self.volume_binder.bind_volumes(task_snapshot)
            self.binder.bind(pod, hostname)
            if mark_sink is not None:
                mark_sink[task_snapshot.uid] = "applied"
            else:
                self._journal_mark(journal_seq, task_snapshot.uid, "applied")
            # Placement-latency ledger: the applied stamp rides the
            # journal-mark seam — the bind LANDED, so this timestamp is
            # the truthful end of the pod's arrival→bind latency.
            from ..obs.latency import LEDGER

            LEDGER.note_applied(task_snapshot.uid)
            if self.cluster is not None:
                self.cluster.record_event(
                    pod, "Normal", "Scheduled",
                    f"Successfully assigned {pod.namespace}/{pod.name} "
                    f"to {hostname}",
                )
        except Exception:
            try:
                self.volume_binder.release_volumes(task_snapshot)
            except Exception:
                logger.exception(
                    "failed to release volumes of %s", task_snapshot.uid
                )
            if mark_sink is not None:
                mark_sink[task_snapshot.uid] = "failed"
            else:
                self._journal_mark(journal_seq, task_snapshot.uid, "failed")
            # Bind failure restarts the pod's latency clock (requeued
            # stage): the next placement is measured from here.
            from ..obs.latency import LEDGER

            LEDGER.note_bind_failed(task_snapshot.uid)
            self._resync_task(task_snapshot)

    def bind(self, task_info: TaskInfo, hostname: str) -> None:
        """reference cache.go:480-522"""
        if self._refused_by_fence(f"bind {task_info.uid} -> {hostname}"):
            raise CacheFencedError(
                f"bind of {task_info.uid} refused: {self._fence_reason}"
            )
        with self.mutex:
            _, task, _ = self._bind_bookkeeping(task_info, hostname)
            pod, task_snapshot = task.pod, task.clone()

        if self.binder is not None:
            def _single_bind():
                # Journal on the worker, not the dispatching cycle (on
                # a real cluster an append is a blocking Lease CAS, and
                # per-task dispatch paths call bind() in a loop); the
                # append still strictly precedes the bind in this job.
                from ..obs.latency import LEDGER
                from ..obs.quality import QUALITY

                LEDGER.note_dispatched((task_snapshot.uid,))
                QUALITY.note_bound((task_snapshot.uid,))
                seq = self._journal_append([task_snapshot])
                self._bind_side_effect(
                    pod, hostname, task_snapshot, journal_seq=seq
                )

            self._submit_side_effect(_single_bind)

    # Batched side-effect jobs are chunked so (a) a 50k-task gang doesn't
    # monopolize one of the pool's workers for its whole serial run and
    # (b) all workers share the bind backlog.
    _BIND_CHUNK = 1024

    def bind_batch(self, task_infos, on_accepted=None) -> list:
        """Batched :meth:`bind`, fully asynchronous: the cache-mirror
        bookkeeping AND the bind side effects run on the side-effect
        pool, overlapping the scheduler's remaining cycle and its
        think-time between cycles — the session works on its own
        snapshot, so nothing in the running cycle reads the cache mirror
        (profile r4: the mirror update alone was ~870 ms of the 50k cold
        apply). :meth:`snapshot` barriers on in-flight side effects, so
        the NEXT cycle observes the completed bookkeeping or waits.

        Returns the input tasks optimistically. The rare task whose
        bookkeeping the node later rejects (solver drift) is reverted to
        its prior status by the async job and rescheduled next cycle —
        the same self-correction contract as the reference's
        assume-then-resync bind (cache.go:480-522)."""
        infos = list(task_infos)
        if infos and self._refused_by_fence(
            f"bind_batch of {len(infos)} tasks"
        ):
            return []
        if not infos:
            if on_accepted is not None:
                try:
                    on_accepted(infos)
                except Exception:  # same contract as the async path
                    logger.exception(
                        "bind_batch on_accepted callback failed"
                    )
            return infos
        self._submit_side_effect(
            lambda: self._bind_batch_bookkeeping(infos, on_accepted),
            bookkeeping=True,
        )
        return infos

    def _bind_batch_bookkeeping(self, task_infos, on_accepted=None) -> list:
        """Under-mutex half of bind_batch + side-effect submission.
        Runs on the side-effect pool. Per-task semantics are bind()'s:
        validation failures are logged and skipped, side-effect failures
        release volumes and resync that task only. Tasks whose volumes
        are NOT ready are submitted as individual jobs — their bind may
        block up to the volume-bind timeout, and a slow volume must not
        head-of-line-block the rest of the gang. Each task_info must have
        node_name set. Returns the tasks whose bookkeeping succeeded."""
        # Journal the batch's intent FIRST — on this worker, not the
        # scheduling loop (on a real cluster an append is a blocking
        # HTTP CAS with retries; the cycle must not pay it). The
        # journal-before-any-side-effect ordering is preserved: every
        # bind of this batch is submitted from THIS job, below, and a
        # crash before this point leaves no cluster write to classify.
        journal_seq = self._journal_append(task_infos)
        binds = []
        slow_binds = []  # volume wait possible: isolate per task
        bound = []
        # Journal marks for tasks that terminally fail DURING the
        # under-mutex staging (validation failure, node revert). The
        # marks are issued AFTER the mutex is released: on a real
        # cluster a mark is an HTTP CAS, and blocking network I/O under
        # cache.mutex is exactly the class kbtlint's lock-order pass
        # forbids (it would stall snapshot/ingest and could trip the
        # watchdog on a slow API server).
        failed_marks: list = []
        with self.mutex:
            # hostname -> [(ti, stored, prior status/node for revert)]
            staged: Dict[str, list] = {}
            by_job: Dict[int, tuple] = {}  # id(job) -> (job, [stored])
            for ti in task_infos:
                try:
                    job, stored, prior = self._bind_bookkeeping(
                        ti, ti.node_name, add_to_node=False,
                        update_status=False,
                    )
                    staged.setdefault(ti.node_name, []).append(
                        (ti, stored, job, prior)
                    )
                    by_job.setdefault(id(job), (job, []))[1].append(stored)
                except Exception:
                    logger.exception(
                        "failed to bind task %s/%s", ti.namespace, ti.name
                    )
                    # Resolve the intent (post-mutex): this task's bind
                    # will never be issued, so an open mark would pin
                    # the record in the journal for the leader's life.
                    failed_marks.append(ti.uid)
            # Status-index moves bulked per job (3rd of the 3 per-task
            # moves on the apply path; see JobInfo.update_tasks_status).
            for job, group in by_job.values():
                job.update_tasks_status(group, TaskStatus.BINDING)

            def accept(ti, stored, hostname):
                snapshot = stored.clone()
                # Volume readiness lives on the CALLER's (session) task —
                # the cache-side clone never sees the session's
                # allocate/bind_volumes writes. Propagate it so the async
                # side effect doesn't re-wait on ready volumes.
                snapshot.volume_ready = ti.volume_ready
                item = (stored.pod, hostname, snapshot)
                # Only a task that could actually block on a volume wait
                # needs per-task isolation: it has claims AND they are
                # not known-bound. A claims-less pod (the overwhelming
                # majority in a batch cluster) can never wait, whatever
                # volume_ready says — routing it to the slow path turns
                # a 50k-task gang into 50k executor submissions.
                may_wait = (
                    not ti.volume_ready and ti.pod.spec.volume_claims
                )
                (slow_binds if may_wait else binds).append(item)
                bound.append(ti)

            def revert(ti, stored, job, prior, hostname, why):
                # The per-task bind() path surfaces a node rejection to
                # its caller by raising; here the caller is gone by
                # side-effect time, so a silently dropped task would sit
                # in BINDING with node_name set and no resync until an
                # external pod event. Revert the staged bookkeeping so
                # the task is schedulable again next cycle.
                prior_status, prior_node = prior
                try:
                    job.update_task_status(stored, prior_status)
                    stored.node_name = prior_node
                    # Drop the claim assumptions made at allocate time,
                    # like the per-task failure path (_bind_side_effect)
                    # — a stale assumption on the rejected host would
                    # fail every future placement of this task.
                    if stored.pod.spec.volume_claims:
                        self.volume_binder.release_volumes(stored)
                except Exception:
                    logger.exception(
                        "failed to revert %s bind %s/%s; resyncing",
                        why, ti.namespace, ti.name,
                    )
                    self._resync_task(stored.clone())
                logger.warning(
                    "node %s %s staged bind of %s/%s; reverted to %s",
                    hostname, why, ti.namespace, ti.name,
                    prior_status.name,
                )
                # A reverted bind is terminally not-applied: resolve
                # the intent (post-mutex) so the record can self-clean.
                failed_marks.append(stored.uid)

            # Node accounting grouped per node (one aggregate idle/used
            # update; fallback policy in NodeInfo.add_tasks_with_fallback).
            for hostname, items in staged.items():
                node = self.nodes.get(hostname)
                if node is None:
                    # A node-delete watch event can land in the async
                    # window between dispatch and bookkeeping. Treat the
                    # whole group as rejected — same revert path — so
                    # the batch's remaining groups still proceed.
                    for ti, stored, job, prior in items:
                        revert(ti, stored, job, prior, hostname,
                               "vanished under")
                    continue
                ok = {
                    id(s) for s in node.add_tasks_with_fallback(
                        [stored for _, stored, _, _ in items]
                    )
                }
                for ti, stored, job, prior in items:
                    if id(stored) in ok:
                        accept(ti, stored, hostname)
                    else:
                        revert(ti, stored, job, prior, hostname,
                               "rejected")

        self._journal_mark_many(
            journal_seq, {uid: "failed" for uid in failed_marks}
        )
        # Placement-latency ledger (outside the mutex): staged binds
        # are DISPATCHED; validation failures / node rejections restart
        # their pods' clocks exactly like an async bind failure.
        from ..obs.latency import LEDGER
        from ..obs.quality import QUALITY

        LEDGER.note_dispatched([t.uid for t in bound])
        QUALITY.note_bound([t.uid for t in bound])
        for uid in failed_marks:
            LEDGER.note_bind_failed(uid, reason="bind-rejected")

        # Pre-warm the COW snapshot pool for everything this batch
        # dirtied: re-clone the touched jobs/nodes HERE, on the
        # bookkeeping worker, so the next cycle's snapshot reuses them
        # instead of paying a full-world re-clone after a busy cycle
        # (steady open was ~200 ms at 50k — pure clone cost). Open cost
        # then scales with what changed SINCE this batch, not with
        # cluster size. Against a live API server, bind-confirmation
        # watch events re-dirty these objects and the next snapshot
        # re-clones them anyway — then the prewarm is wasted worker
        # time, but it never blocks the scheduling loop, and the cycle
        # cost is identical to not prewarming. Per-object lock holds
        # (not one long hold) so a concurrent watch burst interleaves;
        # _snap_pool is re-read under each hold because snapshot()
        # swaps the pool maps. snapshot() cannot run concurrently with
        # this (it barriers on bookkeeping), so entries cannot be lost
        # to a swap mid-loop except on barrier timeout — where dropped
        # entries only cost a re-clone.
        for job, _ in by_job.values():
            with self.mutex:
                self._snap_pool[0][job.uid] = _pool_entry(job)
        for hostname in staged:
            with self.mutex:
                node = self.nodes.get(hostname)
                if node is not None:
                    self._snap_pool[1][hostname] = _pool_entry(node)

        if self.binder is not None:
            def _do_binds(chunk):
                # Chunked drain: journal marks collected per chunk and
                # flushed in one round trip (one Lease CAS on a real
                # cluster) — the fenced case leaves no sink entry, so
                # those intents stay open for the successor.
                marks: Dict[str, str] = {}
                for pod, hostname, task_snapshot in chunk:
                    self._bind_side_effect(
                        pod, hostname, task_snapshot,
                        journal_seq=journal_seq, mark_sink=marks,
                    )
                self._journal_mark_many(journal_seq, marks)

            for start in range(0, len(binds), self._BIND_CHUNK):
                chunk = binds[start:start + self._BIND_CHUNK]
                self._submit_side_effect(lambda c=chunk: _do_binds(c))
            for pod, hostname, task_snapshot in slow_binds:
                self._submit_side_effect(
                    lambda p=pod, h=hostname, s=task_snapshot:
                        self._bind_side_effect(
                            p, h, s, journal_seq=journal_seq
                        )
                )
        if on_accepted is not None:
            try:
                on_accepted(bound)
            except Exception:
                logger.exception("bind_batch on_accepted callback failed")
        return bound

    def evict(self, task_info: TaskInfo, reason: str) -> None:
        """reference cache.go:421-477"""
        if self._refused_by_fence(f"evict {task_info.uid}"):
            raise CacheFencedError(
                f"evict of {task_info.uid} refused: {self._fence_reason}"
            )
        with self.mutex:
            job, task = self._find_job_and_task(task_info)
            node = self.nodes.get(task.node_name)
            if node is None:
                raise KeyError(
                    f"failed to evict Task {task.uid}: host {task.node_name} "
                    f"does not exist"
                )
            self._stamp_dirty(task_info.job, task.node_name)
            job.update_task_status(task, TaskStatus.RELEASING)
            node.update_task(task)
            pod = task.pod
            task_snapshot = task.clone()
            if not shadow_pod_group(job.pod_group) and self.cluster is not None:
                self.cluster.record_event(
                    job.pod_group, "Normal", "Evict", reason
                )
        # Preempt/reclaim eviction restarts the victim's placement
        # clock (requeued stage) — outside the mutex, leaf-lock ledger.
        # The quality monitor counts the same event as disruption churn
        # (and remembers the uid so its next bind counts as a RE-bind).
        from ..obs.latency import LEDGER
        from ..obs.quality import QUALITY

        LEDGER.note_requeued(
            task_info.uid, reason="evicted", job=task_info.job
        )
        QUALITY.note_eviction(task_info.uid, reason)

        def _do_evict():
            if self._refused_by_fence(
                f"evict side effect {pod.namespace}/{pod.name}"
            ):
                return
            try:
                self.evictor.evict(pod)
            except Exception:
                self._resync_task(task_snapshot)

        if self.evictor is not None:
            self._submit_side_effect(_do_evict)

    # -- volumes -------------------------------------------------------------

    def allocate_volumes(self, task: TaskInfo, hostname: str) -> None:
        self.volume_binder.allocate_volumes(task, hostname)

    def allocate_volumes_batch(
        self, tasks, hostname: str, assign_node_name: bool = False
    ) -> list:
        """Batched :meth:`allocate_volumes` for one node's group.
        Claims-less pods (the overwhelming majority) are marked ready in
        one tight loop without a seam call per task; only claim-bearing
        pods go through the per-task binder. Returns the tasks whose
        volume allocation succeeded (failures logged and skipped, like
        the sequential apply loop). ``assign_node_name`` additionally
        stamps ``task.node_name = hostname`` on each successful task —
        the apply path otherwise paid a second full pass for it."""
        ok = []
        append = ok.append
        allocate = self.volume_binder.allocate_volumes
        for task in tasks:
            if task.pod.spec.volume_claims:
                try:
                    allocate(task, hostname)
                except Exception:
                    logger.exception(
                        "Failed to allocate volumes of Task %s on %s",
                        task.uid, hostname,
                    )
                    continue
            else:
                task.volume_ready = True
            if assign_node_name:
                task.node_name = hostname
            append(task)
        return ok

    def bind_volumes(self, task: TaskInfo) -> None:
        """Dispatch-time seam (session.go:294-316 calls BindVolumes before
        Bind). Ready volumes short-circuit here; UNready volumes are bound
        inside the async bind job (cache.bind._do_bind) so a slow volume
        wait never blocks the scheduling loop — a failed/timed-out bind
        there releases the claim assumptions and resyncs the task."""
        if task.volume_ready:
            self.volume_binder.bind_volumes(task)

    # -- status / events -----------------------------------------------------

    def task_unschedulable(self, task: TaskInfo, message: str) -> None:
        """FailedScheduling event + PodScheduled=False condition
        (reference cache.go:533-554)."""
        pod = task.pod
        condition = PodCondition(
            type="PodScheduled", status="False",
            reason="Unschedulable", message=message,
        )
        if self.cluster is not None:
            self.cluster.record_event(pod, "Warning", "FailedScheduling", message)
        if self.status_updater is not None:
            self.status_updater.update_pod_condition(pod, condition)

    def record_job_status_event(self, job: JobInfo) -> None:
        """reference cache.go:695-746"""
        base_message = (
            f"{len(job.task_status_index.get(TaskStatus.PENDING, {}))} pods "
            f"are yet to be scheduled"
        )
        if not job.ready():
            if self.cluster is not None and not shadow_pod_group(job.pod_group):
                self.cluster.record_event(
                    job.pod_group, "Warning", "Unschedulable",
                    f"{job.namespace}/{job.name}: {base_message}",
                )
        # reference cache.go:736-744 iterates [Allocated, Pending].
        job_err_msg = job.fit_error()
        for status in (TaskStatus.ALLOCATED, TaskStatus.PENDING):
            for task in job.task_status_index.get(status, {}).values():
                self.task_unschedulable(task, job_err_msg)

    def update_job_status(self, job: JobInfo) -> JobInfo:
        """Persist PodGroup status (reference cache.go:749-764)."""
        if not shadow_pod_group(job.pod_group):
            pg = job.pod_group
            pg.status.running = len(job.task_status_index.get(TaskStatus.RUNNING, {}))
            pg.status.succeeded = len(
                job.task_status_index.get(TaskStatus.SUCCEEDED, {})
            )
            pg.status.failed = len(job.task_status_index.get(TaskStatus.FAILED, {}))
            if self.status_updater is not None:
                self.status_updater.update_pod_group(pg)
        return job

    # -- lifecycle -----------------------------------------------------------

    def shutdown(self) -> None:
        self._stop.set()
        # Bookkeeping first: its jobs submit side-effect chunks onto the
        # shared pool, so draining it before the shared pool guarantees
        # no post-shutdown submissions.
        self._bookkeeping_executor.shutdown(wait=True)
        self._executor.shutdown(wait=True)
        # Release the solver's device-resident snapshot buffers with the
        # mirror they shadow (accelerator memory outlives nothing).
        dc = getattr(self, "_device_snapshot_cache", None)
        if dc is not None:
            dc.drop()

    # String (reference cache.go String()) omitted; repr is enough.
    def __repr__(self) -> str:
        # Under the mutex: a log line formatting the cache from another
        # thread must not read the maps mid-mutation (kbtlint
        # guarded-by; the mutex is reentrant, so repr-while-held works).
        with self.mutex:
            return (
                f"SchedulerCache(jobs={len(self.jobs)}, "
                f"nodes={len(self.nodes)}, queues={len(self.queues)})"
            )


def new_scheduler_cache(cluster: ClusterAPI, scheduler_name: str, default_queue: str,
                        **kwargs) -> SchedulerCache:
    """reference cache.go:68 New / :223 newSchedulerCache"""
    return SchedulerCache(
        cluster=cluster,
        scheduler_name=scheduler_name,
        default_queue=default_queue,
        **kwargs,
    )
