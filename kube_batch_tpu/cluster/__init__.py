from .api import ADDED, DELETED, MODIFIED, ClusterAPI, InProcessCluster

__all__ = [
    "ADDED", "DELETED", "MODIFIED", "ClusterAPI", "InProcessCluster",
    "KubeCluster", "KubeConfig",
]


def __getattr__(name):
    # Lazy: the real-cluster adapter pulls in yaml/ssl; embedders of the
    # decision core alone must not pay that import (PEP 562).
    if name in ("KubeCluster", "KubeConfig"):
        from . import kube

        return getattr(kube, name)
    raise AttributeError(name)
