"""Pure-hash determinism helpers.

One implementation of the hash-to-[0,1) draw both determinism regimes
rely on — the simulator's per-identity fault decisions
(sim/faults._hash01) and the cluster retry policy's jitter
(cluster/errors.deterministic_jitter). Keyed on stable identities, the
draw is independent of PYTHONHASHSEED and thread timing, so concurrent
callers decide identically at record and replay. Two drifting copies
of this function would silently desynchronize those regimes.
"""

from __future__ import annotations

import hashlib


def hash01(*parts: object) -> float:
    """Stable uniform [0, 1) from identity parts."""
    digest = hashlib.blake2b(
        "\x1f".join(str(p) for p in parts).encode(), digest_size=8
    ).digest()
    return int.from_bytes(digest, "big") / 2**64
