"""Placement-quality scorecard: how WELL the scheduler places.

PR 14's latency SLIs and PR 6's telemetry say how *fast* placement is;
this module scores the *placements themselves*, per cycle, from arrays
the cycle already has (O(nodes + queues + jobs)):

- **packing density** — used/allocatable per resource dimension, both
  cluster-aggregate and node-count-weighted (the mean of per-node
  ratios — a cluster packed onto half its nodes with the other half
  empty scores the same aggregate but a lower node mean + a higher
  emptiable count, which is exactly the consolidation signal);
- **fragmentation** — how many nodes are empty, how many more could be
  *emptied* (their used vectors relocated into the remaining nodes'
  idle headroom, vectorized sorted-prefix water-fill over the idle
  matrix), and per queue the largest gang-member count its biggest
  pending gang could place RIGHT NOW (floor-divide of the idle matrix
  by the gang's per-member request, summed over nodes);
- **fairness** — per-queue signed distance between allocated and the
  water-filled deserved share (same math as the proportion plugin and
  the telemetry fairness probe), plus a Jain index over per-queue
  satisfaction ratios (1.0 = perfectly proportional);
- **disruption churn** — evictions / preemptions / re-binds per
  placement, accumulated by the cache's evict/bind seams and read as
  deltas per card;
- **solver quality rates** — sparse-solve engagement, candidate refill
  (spill) rounds, dense fallbacks, and micro-cycle defers, as counter
  deltas per card.

Everything feeds the established pipeline: telemetry series
(``quality:*``) with soak drift detectors, Prometheus gauges,
``/debug/quality`` + a ``/debug/vars`` block, the flight-record
``quality`` key, and a per-cycle ``quality`` block in the sim trace
(replay-compared minus the ``solver`` sub-dict — counter deltas are
path-dependent across solver modes; density/fairness/churn are pure
functions of the replayed cluster state).

The production feed amortizes the O(nodes) array walk on
``KBT_QUALITY_EVERY`` (default 64, same cadence as the fairness
probe); the simulator computes every cycle (small clusters).
``KBT_QUALITY=0`` disables the scheduler feed entirely. Cards contain
no wall-clock and all floats are rounded, so a card stream is
byte-stable under replay (canonical JSON).
"""

from __future__ import annotations

import logging
import os
from typing import TYPE_CHECKING, Dict, List, Optional, Sequence

from ..utils.lockdebug import witness_writes, wrap_lock

if TYPE_CHECKING:  # pragma: no cover - annotation-only import
    from ..cache import SchedulerCache

logger = logging.getLogger(__name__)

QUALITY_ENV = "KBT_QUALITY"              # "0" disables the feed
QUALITY_EVERY_ENV = "KBT_QUALITY_EVERY"  # production-feed cadence
DEFAULT_QUALITY_EVERY = 64
# The cluster-total Resource sum is O(nodes); refresh like the
# telemetry fairness probe (node-count change or every Nth card).
_NODE_TOTAL_REFRESH = 16
# Eviction-reason values that count as preemption churn (cache.evict
# callers pass these for preempt/reclaim victims).
_PREEMPT_REASONS = frozenset(("preempt", "reclaim"))
# The evicted-uid set exists to classify a later bind as a RE-bind; a
# uid evicted and never re-bound would otherwise pin memory forever on
# a production-length run.
_EVICTED_CAP = 1 << 18


def quality_enabled_from_env() -> bool:
    return os.environ.get(QUALITY_ENV, "1") != "0"


def quality_every_from_env() -> int:
    try:
        return max(1, int(os.environ.get(
            QUALITY_EVERY_ENV, DEFAULT_QUALITY_EVERY
        )))
    except ValueError:
        return DEFAULT_QUALITY_EVERY


def jain_index(values: Sequence[float]) -> float:
    """Jain's fairness index ``(Σx)² / (n·Σx²)`` over non-negative
    satisfaction ratios. Degenerate inputs are *defined*, not NaN: an
    empty vector and an all-zero vector both score 1.0 (a single queue,
    or every queue equally unserved, is perfectly fair)."""
    xs = [float(v) for v in values]
    if not xs:
        return 1.0
    s = sum(xs)
    sq = sum(v * v for v in xs)
    if sq <= 0.0:
        return 1.0
    return (s * s) / (len(xs) * sq)


def _dims_and_eps(nodes) -> "tuple":
    """Stable dimension order (cpu, memory, sorted scalars) + the
    per-dim epsilon vector matching Resource's comparison thresholds."""
    import numpy as np

    from ..api.resource_info import (
        MIN_MEMORY,
        MIN_MILLI_CPU,
        MIN_MILLI_SCALAR,
    )

    scalars = set()
    for node in nodes:
        scalars.update(node.allocatable.scalar_resources or {})
    dims = ["cpu", "memory"] + sorted(scalars)
    eps = np.array(
        [MIN_MILLI_CPU, MIN_MEMORY] + [MIN_MILLI_SCALAR] * len(scalars),
        dtype=np.float64,
    )
    return dims, eps


def _resource_rows(resources, dims) -> "object":
    """[N, R] float64 matrix of Resource vectors in ``dims`` order."""
    import numpy as np

    rows = np.empty((len(resources), len(dims)), dtype=np.float64)
    for j, dim in enumerate(dims):
        if dim == "cpu":
            rows[:, j] = [r.milli_cpu for r in resources]
        elif dim == "memory":
            rows[:, j] = [r.memory for r in resources]
        else:
            rows[:, j] = [
                (r.scalar_resources or {}).get(dim, 0.0)
                for r in resources
            ]
    return rows


def _emptiable_prefix(used, idle, eps) -> int:
    """Largest k such that the k least-loaded non-empty nodes could ALL
    be drained into the remaining nodes' idle headroom (per-dimension,
    epsilon-tolerant). Sorted-prefix water-fill: moving load off the
    least-loaded nodes first is optimal for the count, and feasibility
    is monotone in k (prefix used grows, destination idle shrinks), so
    the answer is the length of the leading feasible run."""
    import numpy as np

    n = used.shape[0]
    if n == 0:
        return 0
    alloc_frac = np.where(
        idle + used > 0.0, used / np.maximum(idle + used, 1e-12), 0.0
    )
    order = np.lexsort((np.arange(n), alloc_frac.max(axis=1)))
    cum_used = np.cumsum(used[order], axis=0)
    cum_idle = np.cumsum(idle[order], axis=0)
    total_idle = idle.sum(axis=0)
    feasible = np.all(
        cum_used <= (total_idle - cum_idle) + eps, axis=1
    )
    bad = np.flatnonzero(~feasible)
    return int(bad[0]) if bad.size else n


def _largest_placeable(idle, req, eps) -> int:
    """How many members of a gang with per-member request ``req`` the
    current idle matrix could hold: Σ_nodes min over requested dims of
    ``floor(idle / req)``."""
    import numpy as np

    mask = req > eps
    if not mask.any():
        return 0
    per_dim = np.floor(
        np.maximum(idle[:, mask], 0.0) / req[mask]
    )
    return int(per_dim.min(axis=1).sum())


def _solver_deltas(state: dict) -> Dict[str, float]:
    """Per-card deltas of the existing solver-quality counters (sparse
    engagement, refill/spill rounds, dense fallbacks, micro defers).
    Path-dependent (excluded from replay comparison)."""
    from .. import metrics

    totals = {
        "sparse_solves": metrics.solver_sparse_solves.total(),
        "refill_rounds": metrics.solver_sparse_refill_rounds.total(),
        "dense_fallbacks": metrics.solver_sparse_dense_fallbacks.total(),
        "micro_deferred": metrics.scheduler_micro_cycles.get(
            ("deferred",)
        ),
    }
    prev = state.setdefault("solver_totals", {})
    out = {
        key: round(float(v - prev.get(key, 0.0)), 6)
        for key, v in totals.items()
    }
    state["solver_totals"] = totals
    return out


def compute_scorecard(
    cache: "SchedulerCache",
    churn: Optional[Dict[str, float]] = None,
    state: Optional[dict] = None,
) -> dict:
    """One placement-quality card from the live cache. ``churn`` is the
    caller's delta dict (evictions/preemptions/rebinds/placements since
    its previous card — the scheduler feed and the simulator each own
    their own deltas so cadences never corrupt each other); ``state``
    memoizes the O(nodes) cluster total and the solver counter totals
    between cards."""
    import numpy as np

    from ..api import Resource
    from ..api.types import TaskStatus
    from ..sim.invariants import water_fill

    state = state if state is not None else {}
    with cache.mutex:
        nodes = [
            cache.nodes[name] for name in sorted(cache.nodes)
            if cache.nodes[name].node is not None
            and cache.nodes[name].ready()
        ]
        dims, eps = _dims_and_eps(nodes)
        alloc = _resource_rows([n.allocatable for n in nodes], dims)
        idle = _resource_rows([n.idle for n in nodes], dims)
        queues = {q.name: q.weight for q in cache.queues.values()}
        n_nodes = len(nodes)
        cards = state.get("cards", 0) + 1
        state["cards"] = cards
        if (
            state.get("n_nodes") != n_nodes
            or cards % _NODE_TOTAL_REFRESH == 1
            or "total" not in state
        ):
            total = Resource.empty()
            for node in nodes:
                total.add(node.allocatable)
            state["total"] = total
            state["n_nodes"] = n_nodes
        total = state["total"]
        allocated = {q: Resource.empty() for q in queues}
        requests = {q: Resource.empty() for q in queues}
        pending_gangs: Dict[str, tuple] = {}
        for job in cache.jobs.values():
            if job.queue not in queues:
                continue
            allocated[job.queue].add(job.allocated)
            requests[job.queue].add(job.total_request)
            pending = job.task_status_index.get(TaskStatus.PENDING)
            if pending:
                rep = pending[min(pending)]
                key = (len(pending), job.uid)
                best = pending_gangs.get(job.queue)
                # Largest pending gang wins; uid breaks ties so the
                # card is replay-deterministic across dict orders.
                if best is None or key > best[0]:
                    pending_gangs[job.queue] = (key, rep.resreq)

    # -- packing density (outside the mutex: pure array math) ---------------
    used = np.clip(alloc - idle, 0.0, None)
    alloc_sum = alloc.sum(axis=0)
    density = {
        dim: round(
            float(used[:, j].sum() / alloc_sum[j])
            if alloc_sum[j] > 0.0 else 0.0,
            6,
        )
        for j, dim in enumerate(dims)
    }
    if n_nodes:
        per_node = np.where(
            alloc > eps, used / np.maximum(alloc, 1e-12), 0.0
        )
        node_mean = {
            dim: round(float(per_node[:, j].mean()), 6)
            for j, dim in enumerate(dims)
        }
    else:
        node_mean = {dim: 0.0 for dim in dims}
    density_dom = max(density.values()) if density else 0.0

    # -- fragmentation -------------------------------------------------------
    empty_mask = (
        np.all(used < eps, axis=1) if n_nodes
        else np.zeros(0, dtype=bool)
    )
    empty_nodes = int(empty_mask.sum())
    emptiable = empty_nodes + _emptiable_prefix(
        used[~empty_mask], idle[~empty_mask], eps
    )
    largest_gang = {}
    for queue in sorted(pending_gangs):
        _key, resreq = pending_gangs[queue]
        req = _resource_rows([resreq], dims)[0]
        largest_gang[queue] = _largest_placeable(idle, req, eps)

    # -- fairness ------------------------------------------------------------
    distance: Dict[str, float] = {}
    satisfaction: List[float] = []
    if len(queues) >= 2:
        deserved = water_fill(total, queues, requests)
        cap_dims = [
            (dim, total.get(dim)) for dim in total.resource_names()
            if total.get(dim) > 0.0
        ]
        for q in sorted(queues):
            drift = 0.0
            for dim, cap in cap_dims:
                d = (allocated[q].get(dim) - deserved[q].get(dim)) / cap
                if abs(d) > abs(drift):
                    drift = d
            distance[q] = round(drift, 6)
            # Satisfaction ratio on the queue's dominant deserved dim:
            # how much of what water-filling owes it does it hold.
            dom = max(
                cap_dims, key=lambda dc: deserved[q].get(dc[0]) / dc[1],
                default=None,
            )
            if dom is not None and deserved[q].get(dom[0]) > 0.0:
                satisfaction.append(
                    min(
                        allocated[q].get(dom[0]) / deserved[q].get(dom[0]),
                        4.0,
                    )
                )
    jain = round(jain_index(satisfaction), 6)

    # -- churn ---------------------------------------------------------------
    churn = dict(churn or {})
    placements = float(churn.get("placements", 0.0))
    evictions = float(churn.get("evictions", 0.0))
    rebinds = float(churn.get("rebinds", 0.0))
    churn_card = {
        "evictions": round(evictions, 6),
        "preemptions": round(float(churn.get("preemptions", 0.0)), 6),
        "rebinds": round(rebinds, 6),
        "placements": round(placements, 6),
        "per_placement": round(
            (evictions + rebinds) / max(1.0, placements), 6
        ),
    }

    return {
        "nodes": n_nodes,
        "queues": len(queues),
        "density": density,
        "density_node_mean": node_mean,
        "density_dom": round(float(density_dom), 6),
        "frag": {
            "empty_nodes": empty_nodes,
            "emptiable_nodes": emptiable,
            "emptiable_frac": round(emptiable / max(1, n_nodes), 6),
            "largest_gang": largest_gang,
        },
        "fairness": {"jain": jain, "distance": distance},
        "churn": churn_card,
        "solver": _solver_deltas(state),
    }


def replay_view(card: Optional[dict]) -> Optional[dict]:
    """The replay-compared projection of a card: everything except the
    ``solver`` counter deltas, which are path-dependent (a two-level
    replay of a flat trace matches placements bit-for-bit but takes
    different refill rounds)."""
    if card is None:
        return None
    return {k: v for k, v in card.items() if k != "solver"}


def telemetry_values(card: dict) -> Dict[str, float]:
    """Flatten a card into the telemetry series the soak drift
    detectors watch (``quality:*``)."""
    values = {
        f"quality:density:{dim}": v
        for dim, v in card.get("density", {}).items()
    }
    values["quality:density_dom"] = float(card.get("density_dom", 0.0))
    fairness = card.get("fairness", {})
    values["quality:fairness_jain"] = float(fairness.get("jain", 1.0))
    values["quality:unfairness"] = round(
        1.0 - float(fairness.get("jain", 1.0)), 6
    )
    frag = card.get("frag", {})
    values["quality:frag_emptiable_frac"] = float(
        frag.get("emptiable_frac", 0.0)
    )
    values["quality:empty_nodes"] = float(frag.get("empty_nodes", 0))
    values["quality:churn_per_placement"] = float(
        card.get("churn", {}).get("per_placement", 0.0)
    )
    return values


class QualityMonitor:
    """Cumulative churn accounting + the amortized production feed.

    The cache's evict/bind seams call :meth:`note_eviction` /
    :meth:`note_bound` (cheap: one lock, counter bumps, a set probe to
    classify re-binds). ``Scheduler.run_once``/``run_micro`` call
    :meth:`annotate_cycle` before closing the flight record; every
    ``KBT_QUALITY_EVERY``-th cycle it computes a card, attaches it to
    the open flight record, and pushes the Prometheus gauges. The
    simulator bypasses the cadence and calls :func:`compute_scorecard`
    directly with its own delta state."""

    def __init__(self):
        self._lock = wrap_lock("obs.quality")
        self.enabled = quality_enabled_from_env()
        self.every = quality_every_from_env()
        self._cycles = 0
        self._cards = 0
        self._state: dict = {}
        self._prev: Dict[str, float] = {}
        self._last_card: Optional[dict] = None
        self.evictions = 0
        self.preemptions = 0
        self.rebinds = 0
        self.bound = 0
        self.evictions_by_reason: Dict[str, int] = {}
        self._evicted: set = set()
        witness_writes(self, "obs.quality", (
            "_cycles", "_cards", "_last_card", "evictions",
            "preemptions", "rebinds", "bound",
        ))

    def reset(self) -> None:
        with self._lock:
            self.enabled = quality_enabled_from_env()
            self.every = quality_every_from_env()
            self._cycles = 0
            self._cards = 0
            self._state = {}
            self._prev = {}
            self._last_card = None
            self.evictions = 0
            self.preemptions = 0
            self.rebinds = 0
            self.bound = 0
            self.evictions_by_reason = {}
            self._evicted = set()

    # -- churn seams (cache/cache.py) ---------------------------------------

    def note_eviction(self, uid: str, reason: str = "") -> None:
        with self._lock:
            self.evictions += 1
            key = reason or "unknown"
            self.evictions_by_reason[key] = (
                self.evictions_by_reason.get(key, 0) + 1
            )
            if reason in _PREEMPT_REASONS:
                self.preemptions += 1
            if len(self._evicted) >= _EVICTED_CAP:
                self._evicted.clear()
            self._evicted.add(uid)
        try:
            from .. import metrics

            metrics.register_quality_eviction(reason or "unknown")
        except Exception:  # pragma: no cover - metrics must never kill
            logger.exception("quality eviction metric failed")

    def note_bound(self, uids: Sequence[str]) -> None:
        if not uids:
            return
        with self._lock:
            self.bound += len(uids)
            rebound = [u for u in uids if u in self._evicted]
            if rebound:
                self.rebinds += len(rebound)
                self._evicted.difference_update(rebound)
        if rebound:
            try:
                from .. import metrics

                metrics.register_quality_rebinds(len(rebound))
            except Exception:  # pragma: no cover
                logger.exception("quality rebind metric failed")

    def counters(self) -> Dict[str, float]:
        with self._lock:
            return {
                "evictions": float(self.evictions),
                "preemptions": float(self.preemptions),
                "rebinds": float(self.rebinds),
                "placements": float(self.bound),
            }

    def churn_delta(self, prev: Dict[str, float]) -> Dict[str, float]:
        """Delta of the cumulative churn counters against ``prev``
        (caller-owned: the scheduler feed and any sim feed each pass
        their own), updating ``prev`` in place."""
        now = self.counters()
        delta = {k: now[k] - prev.get(k, 0.0) for k in now}
        prev.update(now)
        return delta

    # -- the production feed -------------------------------------------------

    def annotate_cycle(
        self, cache: Optional["SchedulerCache"]
    ) -> Optional[dict]:
        """Per-cycle entry point (both cycle kinds — micro cycles count
        toward the cadence exactly like the telemetry probes). On the
        cadence: compute a card, attach it to the OPEN flight record,
        push gauges. Returns the card when one was computed."""
        if not self.enabled or cache is None:
            return None
        with self._lock:
            cycle = self._cycles
            self._cycles += 1
        if cycle % self.every != 0:
            return None
        card = compute_scorecard(
            cache, churn=self.churn_delta(self._prev),
            state=self._state,
        )
        with self._lock:
            self._cards += 1
            self._last_card = card
        from .flightrecorder import RECORDER

        RECORDER.annotate("quality", card)
        try:
            from .. import metrics

            metrics.update_quality(card)
        except Exception:  # pragma: no cover - metrics must never kill
            logger.exception("quality metrics export failed")
        return card

    # -- read side (/debug/quality, /debug/vars) ----------------------------

    def snapshot(self) -> dict:
        with self._lock:
            return {
                "type": "quality",
                "enabled": self.enabled,
                "every": self.every,
                "cycles_seen": self._cycles,
                "cards_computed": self._cards,
                "counters": {
                    "evictions": self.evictions,
                    "preemptions": self.preemptions,
                    "rebinds": self.rebinds,
                    "placements": self.bound,
                    "evictions_by_reason": dict(
                        self.evictions_by_reason
                    ),
                },
                "last": self._last_card,
            }


QUALITY = QualityMonitor()
