"""Cluster-state YAML loader.

The standalone analog of pointing kube-batch at an API server: a YAML
document describing queues, nodes, pod groups, and pods is loaded into the
in-process cluster substrate (reference: config/queue/default.yaml +
example/job.yaml objects, applied by hack/run-e2e-kind.sh:70-79).

Schema (all sections optional)::

    queues:
    - name: default
      weight: 1
      capability: {cpu: "10", memory: 10Gi}    # optional
    nodes:
    - name: n1
      allocatable: {cpu: "32", memory: 128Gi, pods: "110"}
      labels: {zone: us-central2-b}
    podGroups:
    - name: pg1
      namespace: default
      minMember: 3
      queue: default
      priorityClassName: high                  # optional
    pods:
    - name: p1
      namespace: default
      group: pg1                               # via the group annotation
      requests: {cpu: 1000m, memory: 1Gi}
      nodeName: ""                             # pre-bound if set
      phase: Pending
      priority: 10                             # optional
      schedulerName: tpu-batch                 # optional; must match --scheduler-name
    priorityClasses:
    - name: high
      value: 1000
"""

from __future__ import annotations

import yaml

from ..api import PodPhase, PriorityClass, build_resource_list
from ..api.objects import ObjectMeta
from ..cluster import InProcessCluster
from ..utils.test_utils import build_node, build_pod, build_pod_group, build_queue


def _resource_list(d):
    d = dict(d or {})
    cpu = d.pop("cpu", None)
    memory = d.pop("memory", None)
    pods = d.pop("pods", None)
    rl = build_resource_list(
        cpu=cpu, memory=memory,
        pods=int(pods) if pods is not None else None,
    )
    rl.update({k: str(v) for k, v in d.items()})  # scalar resources verbatim
    return rl


def load_cluster_state(path: str, simulate_kubelet: bool = True) -> InProcessCluster:
    """Load either the compact schema above or standard k8s manifests.

    Documents carrying ``apiVersion`` are treated as kube-batch CRD /
    core-v1 manifests (cli/manifests.py) — a reference user's existing
    YAML (example/job.yaml, config/queue/default.yaml) loads unchanged."""
    with open(path) as f:
        docs = [d for d in yaml.safe_load_all(f) if d]
    if docs and any("apiVersion" in d for d in docs):
        from .manifests import apply_manifests

        cluster = InProcessCluster(simulate_kubelet=simulate_kubelet)
        apply_manifests(cluster, docs)
        return cluster
    data = docs[0] if docs else {}
    return build_cluster_from_dict(data, simulate_kubelet=simulate_kubelet)


def build_cluster_from_dict(data: dict, simulate_kubelet: bool = True) -> InProcessCluster:
    cluster = InProcessCluster(simulate_kubelet=simulate_kubelet)
    for q in data.get("queues", []) or []:
        queue = build_queue(
            q["name"], weight=int(q.get("weight", 1)),
            capability=_resource_list(q["capability"]) if q.get("capability") else None,
        )
        cluster.create_queue(queue)
    for pc in data.get("priorityClasses", []) or []:
        cluster.create_priority_class(PriorityClass(
            metadata=ObjectMeta(name=pc["name"]),
            value=int(pc.get("value", 0)),
            global_default=bool(pc.get("globalDefault", False)),
        ))
    for n in data.get("nodes", []) or []:
        cluster.create_node(build_node(
            n["name"], _resource_list(n.get("allocatable")),
            labels=n.get("labels"),
        ))
    for pg in data.get("podGroups", []) or []:
        cluster.create_pod_group(build_pod_group(
            pg["name"], namespace=pg.get("namespace", "default"),
            min_member=int(pg.get("minMember", 1)),
            queue=pg.get("queue", ""),
            priority_class_name=pg.get("priorityClassName", ""),
        ))
    for p in data.get("pods", []) or []:
        pod = build_pod(
            p.get("namespace", "default"), p["name"],
            p.get("nodeName", ""),
            p.get("phase", PodPhase.PENDING),
            _resource_list(p.get("requests")),
            group_name=p.get("group", ""),
            labels=p.get("labels"),
            selector=p.get("nodeSelector"),
            priority=p.get("priority"),
        )
        if "schedulerName" in p:
            pod.spec.scheduler_name = p["schedulerName"]
        cluster.create_pod(pod)
    return cluster
