"""Post-solve placement validation: the last gate before bind dispatch.

The solver's output is DEVICE output — and PR 7's containment treated a
device that raises or hangs, not one that silently miscomputes. A
corrupted assignment vector (bit flip, bad kernel, wedged HBM) that
reached the apply path would become real cluster binds. This module
rechecks every proposed placement host-side, in O(placements) vectorized
work (never O(T·N)):

- **bad-index** — assignment outside [0, N): impossible for a correct
  kernel, certain corruption;
- **infeasible** — the placement violates the feasibility mask the
  solve itself was given (per-element gather of the task's group row —
  no [P, N] materialization);
- **capacity** — a node's aggregate assigned resreq grossly exceeds its
  idle capacity (beyond the per-task epsilon slack a legitimate solve
  can accumulate). Sub-epsilon drift is NOT flagged here: the apply
  path's exact sequential fit guard already degrades that to the
  guarded per-task loop, which re-checks every task individually.

The allocate_tpu ladder consumes the verdict: a device rung whose
output fails validation is treated like a rung failure — breaker fed,
re-solve one rung down — and the native floor drops the offending
placements, so a corrupted result can never reach the cluster
(doc/design/robustness.md).
"""

from __future__ import annotations

from typing import Dict, Tuple

import numpy as np

REJECT_REASONS = ("bad-index", "infeasible", "capacity")


def validate_placements(
    ctx: object, assigned: np.ndarray
) -> Tuple[np.ndarray, Dict[str, int]]:
    """Validate one solve's proposed placements against the feasibility
    mask and a capacity recount. Returns ``(bad_task_indices,
    reason_counts)`` — empty on a clean result. ``ctx`` is the
    tensorize SnapshotContext (mask + host fit/idle arrays)."""
    if (
        ctx.mask is None
        or ctx.task_req_host is None
        or ctx.node_idle_host is None
    ):
        # Host validation arrays absent (legacy direct-solve callers):
        # nothing to validate against — the apply path's sequential fit
        # guard remains the only gate there.
        return np.empty(0, dtype=np.int64), {}
    T = len(ctx.tasks)
    N = len(ctx.nodes)
    a = np.asarray(assigned[:T])
    # Placed = anything that is not the -1 "unassigned" sentinel: a
    # corrupted NEGATIVE index (sign flip) must be rejected as
    # bad-index, not silently read as unplaced — silently dropping a
    # task is exactly the miscompute class this gate exists for.
    sel = np.nonzero(a != -1)[0]
    if sel.size == 0:
        return np.empty(0, dtype=np.int64), {}

    reasons: Dict[str, int] = {}
    nodes_sel = a[sel]
    bad_parts = []

    # 1. bad-index: outside the node universe entirely.
    oob = (nodes_sel >= N) | (nodes_sel < 0)
    if oob.any():
        bad_parts.append(sel[oob])
        reasons["bad-index"] = int(oob.sum())
    ok = ~oob
    sel_ok = sel[ok]
    nodes_ok = nodes_sel[ok].astype(np.int64)
    if sel_ok.size == 0:
        return np.unique(np.concatenate(bad_parts)), reasons

    # 2. infeasible: per-element gather of each task's mask row at its
    # assigned node — O(placements), never a [P, N] materialization.
    mask = ctx.mask
    feas = (
        mask.group_rows[mask.task_group[sel_ok], nodes_ok]
        & mask.node_ok[nodes_ok]
    )
    P = len(mask.pair_idx)
    if P:
        pos = np.clip(np.searchsorted(mask.pair_idx, sel_ok), 0, P - 1)
        has_pair = mask.pair_idx[pos] == sel_ok
        if has_pair.any():
            pair_vals = mask.pair_rows[
                pos[has_pair], nodes_ok[has_pair]
            ]
            feas_pair = feas[has_pair] & pair_vals
            feas = feas.copy()
            feas[has_pair] = feas_pair
    infeasible = ~feas
    if infeasible.any():
        bad_parts.append(sel_ok[infeasible])
        reasons["infeasible"] = int(infeasible.sum())

    # 3. capacity recount: aggregate resreq per node vs idle, with a
    # GENEROUS epsilon (per-task eps × count) so a legitimate solve's
    # accumulated rounding can never trip it — gross oversubscription
    # (a corrupted result concentrating tasks) still does. Offenders =
    # every placement on an overfull node (conservative: the corrupted
    # subset is unidentifiable host-side).
    feas_sel = sel_ok[feas]
    feas_nodes = nodes_ok[feas]
    if feas_sel.size:
        req_rows = ctx.task_req_host[feas_sel]
        R = req_rows.shape[1]
        # bincount per dim, not np.add.at: the unbuffered scatter costs
        # ~3 ms at 50k placements; R bincounts run in tight C loops.
        bins = np.empty((N, R), dtype=np.float64)
        for r in range(R):
            bins[:, r] = np.bincount(
                feas_nodes, weights=req_rows[:, r], minlength=N
            )[:N]
        counts = np.bincount(feas_nodes, minlength=N)[:N].astype(
            np.float64
        )
        eps = ctx.layout.eps().astype(np.float64)
        slack = np.outer(np.maximum(counts, 1.0) + 1.0, eps)
        overfull = (bins > ctx.node_idle_host + slack).any(axis=1)
        if overfull.any():
            on_overfull = overfull[feas_nodes]
            if on_overfull.any():
                bad_parts.append(feas_sel[on_overfull])
                reasons["capacity"] = int(on_overfull.sum())

    if not bad_parts:
        return np.empty(0, dtype=np.int64), {}
    return np.unique(np.concatenate(bad_parts)), reasons
