"""Test configuration: force JAX onto a virtual 8-device CPU mesh.

Must run before any backend resolution so multi-chip sharding paths can be
exercised without TPU hardware (the driver separately dry-runs the real
multi-chip path via __graft_entry__.dryrun_multichip). The heavy lifting —
dropping the site-injected TPU-tunnel PJRT factory before it can dial a
possibly-wedged tunnel, and growing XLA_FLAGS' host device count — lives in
kube_batch_tpu.utils.backend.force_cpu_devices, shared with the entry
points.
"""

import os

# force_cpu_devices pre-imports pallas before purging the tpu platform,
# so the interpret-mode pallas parity tests keep running on CPU.
from kube_batch_tpu.utils.backend import force_cpu_devices

if not force_cpu_devices(8):
    raise RuntimeError(
        "tests need an 8-device virtual CPU mesh, but a jax backend with "
        "fewer devices was already initialized before conftest ran"
    )

# Pin allocate_tpu to the JAX kernel: on a CPU host with a toolchain the
# action would otherwise auto-route to native/greedy.cpp, and the
# accelerator path — the product's main solve path — would lose all its
# action/e2e coverage. Native-route tests override per-test via
# monkeypatch.setenv.
os.environ.setdefault("KBT_SOLVER", "jax")
