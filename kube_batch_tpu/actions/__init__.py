"""Actions (mirrors reference pkg/scheduler/actions).

Importing this package registers every builtin action with the framework
registry (the reference's factory.go:28-33 / init() pattern), including the
TPU-native ``allocate_tpu`` batched drop-in."""

from . import allocate, allocate_tpu, backfill, preempt, reclaim  # noqa: F401
