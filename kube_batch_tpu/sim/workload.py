"""Seeded synthetic workload generator.

Emits per-cycle EVENT DICTS (the trace's lingua franca — the harness
applies the same dicts whether they come from this generator or from a
replayed trace): gang arrivals drawn from a size/req mix, completions
after a seeded fully-running duration, and planned node add/drain
churn. All randomness flows from one named ``random.Random`` stream so
a (seed, spec) pair always yields the same event sequence; nothing here
reads the wall clock (timestamps are virtual-time values the harness
passes in).
"""

from __future__ import annotations

import random
from dataclasses import dataclass, field
from typing import Dict, List, Sequence, Tuple


@dataclass
class WorkloadSpec:
    """Knobs of the synthetic cluster + arrival process."""

    nodes: int = 12
    node_cpu_m: int = 8000          # per-node allocatable millicores
    node_mem_mi: int = 16384        # per-node allocatable MiB
    queues: Dict[str, int] = field(
        default_factory=lambda: {"default": 1, "batch": 2}
    )
    # (gang size, weight) mix; min_member == size (full gangs).
    gang_sizes: Sequence[Tuple[int, float]] = (
        (1, 0.45), (2, 0.25), (4, 0.2), (8, 0.1)
    )
    # (cpu_m, mem_mi, weight) per-task request mix.
    reqs: Sequence[Tuple[int, int, float]] = (
        (500, 512, 0.6), (1000, 1024, 0.3), (2000, 2048, 0.1)
    )
    arrival_rate: float = 1.5       # expected job arrivals per cycle
    # Arrival profile (the high-arrival SLI mixes, obs/latency.py):
    # - "poisson":   seeded Poisson draws at arrival_rate (default);
    # - "sustained": exactly round(arrival_rate) jobs EVERY cycle — a
    #   flat firehose with no draw jitter (the 10k+ arrivals/s-
    #   equivalent sustained mix is this with a large rate);
    # - "burst":     Poisson base rate plus a spike of burst_size jobs
    #   every burst_every cycles (thundering-herd arrival waves).
    arrival_profile: str = "poisson"
    burst_every: int = 16           # cycles between burst spikes
    burst_size: int = 64            # jobs per burst spike
    duration_cycles: Tuple[int, int] = (4, 16)  # fully-running lifetime
    max_jobs_in_flight: int = 64    # arrival back-pressure bound
    # Planned churn: per-cycle probability of one node-add / node-drain
    # event (drain deletes the node; its pods are killed and recreated
    # as Pending by the harness — the replicaset-controller analog).
    node_add_rate: float = 0.0
    node_drain_rate: float = 0.0
    min_nodes: int = 4
    max_nodes: int = 64

    def to_dict(self) -> dict:
        return {
            "nodes": self.nodes,
            "node_cpu_m": self.node_cpu_m,
            "node_mem_mi": self.node_mem_mi,
            "queues": dict(self.queues),
            "gang_sizes": [list(g) for g in self.gang_sizes],
            "reqs": [list(r) for r in self.reqs],
            "arrival_rate": self.arrival_rate,
            "arrival_profile": self.arrival_profile,
            "burst_every": self.burst_every,
            "burst_size": self.burst_size,
            "duration_cycles": list(self.duration_cycles),
            "max_jobs_in_flight": self.max_jobs_in_flight,
            "node_add_rate": self.node_add_rate,
            "node_drain_rate": self.node_drain_rate,
            "min_nodes": self.min_nodes,
            "max_nodes": self.max_nodes,
        }


def _poisson(rng: random.Random, lam: float) -> int:
    """Knuth inverse-transform Poisson sample off the seeded stream."""
    if lam <= 0:
        return 0
    import math

    limit = math.exp(-lam)
    k, p = 0, 1.0
    while True:
        p *= rng.random()
        if p <= limit:
            return k
        k += 1


def _weighted(rng: random.Random, mix: Sequence[tuple]):
    """Pick an entry from a (..., weight) mix."""
    total = sum(m[-1] for m in mix)
    x = rng.random() * total
    for m in mix:
        x -= m[-1]
        if x <= 0:
            return m
    return mix[-1]


class WorkloadGenerator:
    """Per-cycle event emitter; the harness feeds back observed state
    (which jobs are fully running, which nodes exist) through the
    ``running_since`` / ``node_names`` arguments — both derived from
    deterministic cluster state, so the feedback loop stays replayable."""

    def __init__(self, spec: WorkloadSpec, seed: int):
        self.spec = spec
        self.rng = random.Random(f"{seed}/workload")
        self._job_seq = 0
        self._node_seq = spec.nodes
        # name -> {"duration": d, "min_member": m}; jobs the generator
        # considers alive (created, not yet deleted).
        self.alive: Dict[str, dict] = {}
        self._pending_delete: List[str] = []

    # -- bootstrap -----------------------------------------------------------

    def initial_events(self) -> List[dict]:
        events = [
            {"kind": "queue-add", "name": name, "weight": weight}
            for name, weight in sorted(self.spec.queues.items())
        ]
        events.extend(
            self._node_event(f"sim-node-{i:03d}")
            for i in range(self.spec.nodes)
        )
        return events

    def _node_event(self, name: str) -> dict:
        return {
            "kind": "node-add",
            "name": name,
            "cpu_m": self.spec.node_cpu_m,
            "mem_mi": self.spec.node_mem_mi,
        }

    # -- per cycle -----------------------------------------------------------

    def events_for_cycle(
        self,
        cycle: int,
        running_since: Dict[str, int],
        node_names: Sequence[str],
    ) -> List[dict]:
        spec, rng = self.spec, self.rng
        events: List[dict] = []

        # Deletions scheduled by last cycle's completions run first so
        # the job's Succeeded pods leave before new arrivals land.
        for name in self._pending_delete:
            events.append({"kind": "job-delete", "name": name})
            self.alive.pop(name, None)
        self._pending_delete = []

        # Completions: a job that has been fully running for its seeded
        # duration succeeds now and is deleted next cycle (exercising
        # the terminated-job cleanup path in between).
        for name in sorted(self.alive):
            since = running_since.get(name)
            if since is None:
                continue
            if cycle - since >= self.alive[name]["duration"]:
                events.append({"kind": "job-complete", "name": name})
                self._pending_delete.append(name)

        # Node churn (planned, seeded).
        n_nodes = len(node_names)
        if (
            spec.node_add_rate > 0
            and n_nodes < spec.max_nodes
            and rng.random() < spec.node_add_rate
        ):
            name = f"sim-node-{self._node_seq:03d}"
            self._node_seq += 1
            events.append(self._node_event(name))
        if (
            spec.node_drain_rate > 0
            and n_nodes > spec.min_nodes
            and rng.random() < spec.node_drain_rate
        ):
            victim = rng.choice(sorted(node_names))
            events.append(
                {"kind": "node-remove", "name": victim, "reason": "drain"}
            )

        # Arrivals (profile-shaped; every random draw stays on the one
        # seeded stream so (seed, spec) still pins the event sequence).
        if spec.arrival_profile == "sustained":
            arrivals = max(0, int(round(spec.arrival_rate)))
        else:
            arrivals = _poisson(rng, spec.arrival_rate)
            if (
                spec.arrival_profile == "burst"
                and spec.burst_every > 0
                and cycle % spec.burst_every == 0
            ):
                arrivals += max(0, int(spec.burst_size))
        for _ in range(arrivals):
            if len(self.alive) - len(self._pending_delete) >= (
                spec.max_jobs_in_flight
            ):
                break
            size = int(_weighted(rng, spec.gang_sizes)[0])
            cpu_m, mem_mi, _ = _weighted(rng, spec.reqs)
            queue = sorted(spec.queues)[
                rng.randrange(len(spec.queues))
            ]
            duration = rng.randint(*spec.duration_cycles)
            name = f"simjob-{self._job_seq:05d}"
            self._job_seq += 1
            self.alive[name] = {"duration": duration, "min_member": size}
            events.append({
                "kind": "job-create",
                "name": name,
                "queue": queue,
                "replicas": size,
                "min_member": size,
                "cpu_m": int(cpu_m),
                "mem_mi": int(mem_mi),
                "duration": duration,
            })
        return events

