"""Chaos-path coverage: the deterministic simulator under a
solver-exc + solver-hang + bind fault storm must keep every invariant,
complete every cycle (the ladder absorbs device faults inside the
cycle), re-promote the device path when faults stop, and replay
bit-identically. doc/design/robustness.md."""

import pytest

from kube_batch_tpu.metrics import metrics as m
from kube_batch_tpu.sim.faults import parse_fault_spec
from kube_batch_tpu.sim.harness import ClusterSimulator, SimConfig
from kube_batch_tpu.sim.trace import TraceReader

STORM = "solver-exc:0.05,solver-hang:0.01,bind:0.05"


def _storm_cfg(cycles, tmp_path, seed=11, faults=STORM):
    return SimConfig(
        cycles=cycles, seed=seed, faults=faults, backend="dense",
        trace_path=str(tmp_path / "chaos.jsonl"),
    )


def _run(cfg):
    sim = ClusterSimulator(cfg)
    return sim.run()


class TestChaosStorm:
    def test_storm_completes_clean_and_replays_bit_equal(self, tmp_path):
        # Fault rates scaled up so a CI-sized run still injects a
        # meaningful storm (~15 exc + ~3 hangs over 150 cycles).
        cfg = _storm_cfg(
            150, tmp_path,
            faults="solver-exc:0.1,solver-hang:0.02,bind:0.05",
        )
        fallbacks_before = m.solver_fallback.get(
            ("dense", "native", "exception")
        )
        report = _run(cfg)
        assert report.violations == []
        assert report.cycle_errors == 0  # every fault contained in-cycle
        assert report.fault_counts.get("solver-exc", 0) > 0
        assert report.fault_counts.get("solver-hang", 0) > 0
        assert report.fault_counts.get("bind", 0) > 0
        # The ladder actually ran: device-rung descents were recorded.
        assert m.solver_fallback.get(
            ("dense", "native", "exception")
        ) > fallbacks_before
        # Hangs quarantined the backend at least once, and the breaker
        # re-promoted once the fault windows closed.
        assert report.breaker is not None
        assert report.breaker["trips"] >= 1
        assert report.breaker["reclosures"] >= 1
        assert report.breaker["state"] == "closed"
        assert report.placements > 0

        # Bit-equal replay: same placements every recorded cycle, same
        # invariant cleanliness — breaker state and fault windows are
        # cycle-counted, so record and replay walk the same ladder.
        replay_cfg = SimConfig(
            replay=TraceReader.load(str(tmp_path / "chaos.jsonl")),
            backend="dense",
        )
        replayed = _run(replay_cfg)
        assert replayed.replay_mismatches == []
        assert replayed.violations == []
        assert replayed.cycle_errors == 0

    def test_backend_loss_window_holds_breaker_open(self, tmp_path):
        cfg = _storm_cfg(
            80, tmp_path, seed=5, faults="backend-loss:0.05",
        )
        report = _run(cfg)
        assert report.violations == []
        assert report.cycle_errors == 0
        assert report.fault_counts.get("backend-loss", 0) > 0
        # Lost-backend cycles fail the solve AND the canary, so the
        # breaker opened and had failing probes before re-promoting.
        assert report.breaker["trips"] >= 1
        assert report.breaker["state"] == "closed"

    @pytest.mark.slow
    def test_storm_2k_cycles(self, tmp_path):
        """The acceptance-criteria soak (also run by `make chaos-smoke`
        at a CI-friendly size): 2k cycles under the issue's exact storm
        spec, zero violations, zero wedges, breaker re-promoted,
        bit-equal replay."""
        cfg = _storm_cfg(2000, tmp_path)
        report = _run(cfg)
        assert report.violations == []
        assert report.cycle_errors == 0
        assert report.breaker["state"] == "closed"
        assert report.breaker["trips"] >= 1
        replay_cfg = SimConfig(
            replay=TraceReader.load(str(tmp_path / "chaos.jsonl")),
            backend="dense",
        )
        replayed = _run(replay_cfg)
        assert replayed.replay_mismatches == []
        assert replayed.violations == []


class TestFaultSpec:
    def test_new_kinds_parse(self):
        spec = parse_fault_spec(STORM + ",backend-loss:0.01")
        assert spec["solver-exc"] == 0.05
        assert spec["solver-hang"] == 0.01
        assert spec["backend-loss"] == 0.01

    def test_unknown_kind_still_rejected(self):
        with pytest.raises(ValueError):
            parse_fault_spec("solver-oops:0.1")

    def test_device_kinds_rejected_on_native_backend(self, tmp_path):
        """--backend native never dispatches a device solve, so device
        fault kinds would count injections while exercising nothing —
        a vacuous chaos run must be rejected up front."""
        cfg = SimConfig(
            cycles=10, seed=1, faults="solver-exc:0.1",
            backend="native",
            trace_path=str(tmp_path / "t.jsonl"),
        )
        with pytest.raises(ValueError, match="device backend"):
            ClusterSimulator(cfg)

    def test_tiny_solve_budget_only_with_device_faults(self, tmp_path):
        """The 0.5 s wall-clock budget exists to cap INJECTED hangs; a
        fault-free run must keep the generous production budget, or a
        contended CI box turns a healthy solve's scheduling stall into
        a SolveTimeout cycle error (soak flake)."""
        from kube_batch_tpu.solver import containment

        cfg = SimConfig(
            cycles=5, seed=1, faults="bind:0.05", backend="dense",
            trace_path=str(tmp_path / "a.jsonl"),
        )
        sim = ClusterSimulator(cfg)
        try:
            assert containment.solve_budget() >= 30.0
        finally:
            sim.close()

        cfg2 = SimConfig(
            cycles=5, seed=1, faults="solver-hang:0.05",
            backend="dense",
            trace_path=str(tmp_path / "b.jsonl"),
        )
        sim2 = ClusterSimulator(cfg2)
        try:
            assert containment.solve_budget() == 0.5
        finally:
            sim2.close()
