"""Known-bad shape-contracts fixture: one violation per check —
undeclared field, stale table row, comment/table shape drift, row-axis
disagreement, producer dropping a field, out-of-range stack index."""

from typing import NamedTuple

SOLVER_INPUT_CONTRACTS = {
    "task_req": {"shape": ["T", "R"], "dtype": "f32"},
    "ghost_field": {"shape": ["N"], "dtype": "i32"},
}

PACKED_INPUT_CONTRACTS = {
    "task_f32": {"shape": [2, "T", "R"], "dtype": "f32",
                 "row_axis": 1, "donated": True},
    "task_i32": {"shape": [6, "T"], "dtype": "i32",
                 "row_axis": 1, "donated": True},
    "node_f32": {"shape": [3, "N", "R"], "dtype": "f32",
                 "row_axis": 1, "donated": True},
    "node_i32": {"shape": [3, "N"], "dtype": "i32",
                 "row_axis": 1, "donated": True},
    "queue_f32": {"shape": [2, "Q", "R"], "dtype": "f32",
                  "row_axis": 1, "donated": True},
    "misc": {"shape": ["R+2"], "dtype": "f32",
             "row_axis": 0, "donated": True},
}

_ROW_AXIS = {
    "task_f32": 1,
    "task_i32": 0,  # disagrees with the declared row_axis 1
    "node_f32": 1,
    "node_i32": 1,
    "queue_f32": 1,
    "misc": 0,
}


class SolverInputs(NamedTuple):
    task_req: object    # f32[T, R] request rows
    task_extra: object  # i32[T] undeclared: no contract table entry


class PackedInputs(NamedTuple):
    task_f32: object  # [3, T, R] drifted comment (table says [2, T, R])
    task_i32: object  # i32[6, T] rank, queue, job, group, valid, cand
    node_f32: object  # [3, N, R] idle, releasing, cap
    node_i32: object  # [3, N] task_count, max_tasks, feas
    queue_f32: object  # [2, Q, R] deserved, allocated
    misc: object      # f32[R+2] eps, weights


def pack(stack, task_req, task_fit, task_rows, nodes, node_rows, queues):
    return {  # ships no "misc": producer census must flag it
        "task_f32": stack([task_req, task_fit]),
        "task_i32": stack(task_rows),
        "node_f32": stack(nodes),
        "node_i32": stack(node_rows),
        "queue_f32": stack(queues),
    }


def unpack(p):
    return p.node_i32[3]  # stack height is 3: one past the end
