"""Warm-started steady cycles: bit parity with a cold scheduler.

The warm-start state machine (solver/warm.py) skips or shrinks the
solve when its delta preconditions prove the previous cycle's verdicts
still hold. The contract pinned here: a scheduler running with the warm
path ENABLED must leave bit-identical cluster state — per-task
placements and per-node idle accounting — to a scheduler running every
cycle cold (KBT_WARM=0), across randomized placement-wave, arrival,
completion, node-death and eviction sequences. Fallback cycles count as
parity too: the machine's job is to never be wrong, not to always
engage.

Also here: the narrow dirty ledger's semantics (bind bookkeeping
stamps narrow, third-party events win), warm-noop engagement stats,
micro-cycle behavior (placement through the warm path only, deferral
otherwise, flight-record cycle_kind), the incremental-snapshot parity
against the forced full walk, and the zero-new-jits warm-path retrace
guard.
"""

import os

import numpy as np
import pytest

import kube_batch_tpu.actions  # noqa: F401 (registers actions)
import kube_batch_tpu.plugins  # noqa: F401 (registers plugins)
from kube_batch_tpu.api import PodPhase, TaskStatus, build_resource_list
from kube_batch_tpu.framework import close_session, get_action, open_session
from kube_batch_tpu.actions.allocate_tpu import last_stats
from kube_batch_tpu.utils.test_utils import (
    build_node,
    build_pod,
    build_pod_group,
    build_queue,
)

from tests.actions.test_actions import DEFAULT_TIERS_ARGS, make_cache, make_tiers


def _env(key, value):
    """Set/unset an env var, returning the previous value."""
    prev = os.environ.get(key)
    if value is None:
        os.environ.pop(key, None)
    else:
        os.environ[key] = value
    return prev


class _ScenarioDriver:
    """Replays one seeded event script against a fresh cache+action."""

    def __init__(self, seed, nodes=8, queues=2):
        self.rng = np.random.RandomState(seed)
        self.nodes = nodes
        self.queues = queues

    def script(self, kinds, cycles):
        """Generate a deterministic per-cycle event list: each entry is
        (kind, payload) applied through the cache watch entry points."""
        rng = self.rng
        script = []
        gang_n = [0]
        for cycle in range(cycles):
            events = []
            for kind in kinds:
                if kind == "arrival" and rng.rand() < 0.8:
                    g = gang_n[0]
                    gang_n[0] += 1
                    size = int(rng.randint(1, 6))
                    events.append(("gang", (f"g{g}", size, int(rng.randint(
                        1, size + 1)), f"q{int(rng.randint(0, self.queues))}",
                        int(rng.choice([250, 500, 1000, 2000])),
                        int(rng.choice([256, 512, 1024])))))
                elif kind == "wave" and cycle == 0:
                    for g in range(6):
                        gg = gang_n[0]
                        gang_n[0] += 1
                        events.append(("gang", (f"g{gg}", 6, 2,
                                       f"q{gg % self.queues}", 500, 512)))
                elif kind == "completion" and cycle >= 2 and rng.rand() < 0.5:
                    events.append(("complete", int(rng.randint(0, 1 << 30))))
                elif kind == "node-death" and cycle == cycles // 2:
                    events.append(("kill-node", int(rng.randint(0, self.nodes))))
                elif kind == "evict" and cycle >= 2 and rng.rand() < 0.4:
                    events.append(("evict", int(rng.randint(0, 1 << 30))))
            script.append(events)
        return script

    def run(self, script, warm: bool):
        prev = _env("KBT_WARM", None if warm else "0")
        try:
            cache = make_cache()
            for q in range(self.queues):
                cache.add_queue(build_queue(f"q{q}", weight=q + 1))
            for j in range(self.nodes):
                cache.add_node(build_node(
                    f"n{j}",
                    build_resource_list(cpu="8", memory="32Gi", pods=110),
                ))
            action, _ = get_action("allocate_tpu")
            tiers = make_tiers(*DEFAULT_TIERS_ARGS)
            states = []
            outcomes = []
            for events in script:
                self._apply(cache, events)
                ssn = open_session(cache, tiers)
                action.execute(ssn)
                outcomes.append(last_stats.get("warm_outcome"))
                close_session(ssn)
                assert cache.wait_for_side_effects(timeout=30.0)
                assert cache.wait_for_bookkeeping(timeout=30.0)
                states.append(self._state(cache))
            cache.shutdown()
            return states, outcomes
        finally:
            _env("KBT_WARM", prev)

    def _apply(self, cache, events):
        for kind, payload in events:
            if kind == "gang":
                name, size, min_member, queue, cpu, mem = payload
                cache.add_pod_group(build_pod_group(
                    name, namespace="ns", min_member=min_member, queue=queue,
                ))
                for i in range(size):
                    cache.add_pod(build_pod(
                        "ns", f"{name}-p{i}", "", PodPhase.PENDING,
                        build_resource_list(
                            cpu=f"{cpu}m", memory=f"{mem}Mi"
                        ),
                        group_name=name,
                    ))
            elif kind == "gang-head":
                # First HEAD pods of a split gang: the group object and
                # the head arrive this cycle, the tail next cycle — the
                # micro/periodic boundary shape for gang arrivals.
                name, size, min_member, queue, cpu, mem, head = payload
                cache.add_pod_group(build_pod_group(
                    name, namespace="ns", min_member=min_member, queue=queue,
                ))
                for i in range(head):
                    cache.add_pod(build_pod(
                        "ns", f"{name}-p{i}", "", PodPhase.PENDING,
                        build_resource_list(
                            cpu=f"{cpu}m", memory=f"{mem}Mi"
                        ),
                        group_name=name,
                    ))
            elif kind == "gang-tail":
                name, size, min_member, queue, cpu, mem, head = payload
                for i in range(head, size):
                    cache.add_pod(build_pod(
                        "ns", f"{name}-p{i}", "", PodPhase.PENDING,
                        build_resource_list(
                            cpu=f"{cpu}m", memory=f"{mem}Mi"
                        ),
                        group_name=name,
                    ))
            elif kind == "complete":
                bound = self._bound_tasks(cache)
                if bound:
                    task = bound[payload % len(bound)]
                    pod = task.pod
                    pod.status.phase = PodPhase.SUCCEEDED
                    cache.delete_pod(pod)
            elif kind == "kill-node":
                name = f"n{payload % self.nodes}"
                node = cache.nodes.get(name)
                if node is not None and node.node is not None:
                    cache.delete_node(node.node)
            elif kind == "evict":
                bound = self._bound_tasks(cache)
                if bound:
                    task = bound[payload % len(bound)]
                    try:
                        cache.evict(task, "test-preempt")
                    except Exception:
                        pass

    @staticmethod
    def _bound_tasks(cache):
        out = []
        with cache.mutex:
            for key in sorted(cache.jobs):
                job = cache.jobs[key]
                for uid in sorted(job.tasks):
                    t = job.tasks[uid]
                    if t.status == TaskStatus.BINDING and t.node_name:
                        out.append(t)
        return out

    @staticmethod
    def _state(cache):
        """Settled mirror truth: placements + exact idle accounting."""
        with cache.mutex:
            jobs = {
                key: sorted(
                    (uid, t.status.name, t.node_name)
                    for uid, t in job.tasks.items()
                )
                for key, job in cache.jobs.items()
            }
            nodes = {
                name: (
                    n.idle.milli_cpu, n.idle.memory,
                    n.used.milli_cpu, n.used.memory,
                    len(n.tasks),
                )
                for name, n in cache.nodes.items()
            }
        return jobs, nodes


SCENARIOS = {
    "placement-wave": (["wave", "arrival"], 8),
    "arrival": (["arrival"], 10),
    "completion": (["wave", "arrival", "completion"], 10),
    "node-death": (["wave", "arrival", "node-death"], 8),
    "preempt": (["wave", "arrival", "evict"], 10),
}


class TestWarmColdBitParity:
    @pytest.mark.parametrize("scenario", sorted(SCENARIOS))
    def test_randomized_churn_parity(self, scenario):
        kinds, cycles = SCENARIOS[scenario]
        for seed in (3, 17):
            driver = _ScenarioDriver(seed)
            script = driver.script(kinds, cycles)
            warm_states, warm_outcomes = _ScenarioDriver(seed).run(
                script, warm=True
            )
            cold_states, cold_outcomes = _ScenarioDriver(seed).run(
                script, warm=False
            )
            assert all(o == "disabled" for o in cold_outcomes)
            for c, (w, k) in enumerate(zip(warm_states, cold_states)):
                assert w == k, (
                    f"{scenario} seed {seed}: warm/cold state diverged "
                    f"at cycle {c} (warm outcome "
                    f"{warm_outcomes[c]!r})"
                )

    def test_arrival_scenario_actually_engages_warm(self):
        driver = _ScenarioDriver(5)
        script = driver.script(["arrival"], 10)
        _, outcomes = _ScenarioDriver(5).run(script, warm=True)
        # First cycle is cold; after that the pure-arrival stream must
        # ride the warm path (solve for new work, noop when a cycle's
        # rand produced no gang).
        assert set(outcomes[1:]) <= {"solve", "noop"}, outcomes
        assert "solve" in outcomes[1:]

    def test_churn_events_fold_into_subset_not_full_solve(self):
        """Third-party churn (node death, mutated carried jobs, queue
        budget moves) no longer voids the whole warm plan: the affected
        carried work is FORCED into the rank-stable subset and
        re-solved, so the stream keeps engaging. The only full-solve
        outcomes left in a churny stream are the first cycle's cold
        start and a node event landing on a tick with no pending work
        anywhere."""
        driver = _ScenarioDriver(9)
        script = driver.script(["wave", "arrival", "node-death"], 8)
        _, outcomes = _ScenarioDriver(9).run(script, warm=True)
        assert outcomes[0] == "cold"
        assert not set(outcomes[1:]) & {
            "stale", "carried-changed", "deserved-changed",
        }, outcomes
        assert set(outcomes[1:]) <= {
            "solve", "subset", "noop", "node-dirty",
        }, outcomes
        assert set(outcomes[1:]) & {"solve", "subset"}, outcomes


class TestCongestedSubsetParity:
    """Congested-regime scripts: the opening wave over-subscribes a
    2-node cluster so a real carried backlog forms, and new arrivals
    interleave with it every cycle — the warm machine must answer with
    rank-stable SUBSET solves, not full re-solves. Contract: placements
    and idle accounting stay bit-identical to KBT_WARM=0 across every
    cycle, AND the subset path actually engages (a script that never
    reaches ``subset`` proves nothing about it)."""

    def _run_script_pair(self, seed, script, nodes):
        warm_states, warm_outcomes = _ScenarioDriver(
            seed, nodes=nodes
        ).run(script, warm=True)
        cold_states, _ = _ScenarioDriver(
            seed, nodes=nodes
        ).run(script, warm=False)
        for c, (w, k) in enumerate(zip(warm_states, cold_states)):
            assert w == k, (
                f"seed {seed}: warm/cold state diverged at cycle {c} "
                f"(warm outcome {warm_outcomes[c]!r})"
            )
        return warm_outcomes

    def _run_pair(self, seed, kinds, cycles, nodes=2):
        driver = _ScenarioDriver(seed, nodes=nodes)
        script = driver.script(kinds, cycles)
        return self._run_script_pair(seed, script, nodes)

    def test_carried_new_interleave_parity(self):
        outcomes = self._run_pair(21, ["wave", "arrival"], 10)
        assert "subset" in outcomes, outcomes

    def test_preempt_mid_backlog_parity(self):
        outcomes = self._run_pair(23, ["wave", "arrival", "evict"], 10)
        assert "subset" in outcomes, outcomes

    def test_completion_churn_mid_backlog_parity(self):
        outcomes = self._run_pair(
            25, ["wave", "arrival", "completion"], 12
        )
        assert "subset" in outcomes, outcomes

    def test_gang_spanning_cycle_boundary_parity(self):
        # One gang's pods arrive split across a cycle boundary: the
        # head lands gated below min_member while a backlog is carried,
        # the tail completes the gang one cycle later, and completions
        # then free capacity so the backlog drains through subset
        # solves.
        script = [
            [("gang", ("gb0", 6, 2, "q0", 2000, 1024)),
             ("gang", ("gb1", 6, 2, "q1", 2000, 1024)),
             ("gang-head", ("gs", 4, 4, "q0", 500, 512, 2))],
            [("gang-tail", ("gs", 4, 4, "q0", 500, 512, 2))],
            [("complete", 0), ("complete", 1)],
            [("gang", ("gn", 2, 1, "q1", 500, 512))],
            [("complete", 2)],
            [],
        ]
        outcomes = self._run_script_pair(29, script, nodes=2)
        assert "subset" in outcomes, outcomes


class TestNarrowLedger:
    def _cluster(self):
        cache = make_cache()
        cache.add_queue(build_queue("q0", weight=1))
        for j in range(4):
            cache.add_node(build_node(
                "nn%d" % j, build_resource_list(cpu="8", memory="32Gi"),
            ))
        cache.add_pod_group(build_pod_group(
            "pg0", namespace="ns", min_member=1, queue="q0",
        ))
        for i in range(4):
            cache.add_pod(build_pod(
                "ns", f"pg0-p{i}", "", PodPhase.PENDING,
                build_resource_list(cpu="500m", memory="512Mi"),
                group_name="pg0",
            ))
        return cache

    def test_bind_bookkeeping_stamps_narrow(self):
        cache = self._cluster()
        action, _ = get_action("allocate_tpu")
        ssn = open_session(cache, make_tiers(*DEFAULT_TIERS_ARGS))
        action.execute(ssn)
        close_session(ssn)
        assert cache.wait_for_side_effects(timeout=30.0)
        assert cache.wait_for_bookkeeping(timeout=30.0)
        snap = cache.snapshot()
        # Placements landed through bind bookkeeping only: every dirty
        # name is NARROW.
        assert snap.dirty_nodes_narrow
        assert not snap.dirty_nodes
        assert snap.dirty_jobs_narrow == frozenset({"ns/pg0"})
        assert not snap.dirty_jobs
        cache.shutdown()

    def test_allocated_status_flip_stamps_narrow(self):
        """A kubelet/bind-confirmation pod MODIFIED (same pod, same
        node, allocated→allocated status, same resreq) is a pure
        confirmation of the scheduler's own placement: it must stamp
        NARROW, or live clusters re-dirty every node one cycle after
        each bind and the warm path can never engage."""
        cache = self._cluster()
        action, _ = get_action("allocate_tpu")
        ssn = open_session(cache, make_tiers(*DEFAULT_TIERS_ARGS))
        action.execute(ssn)
        close_session(ssn)
        assert cache.wait_for_side_effects(timeout=30.0)
        assert cache.wait_for_bookkeeping(timeout=30.0)
        cache.snapshot()  # drain the bind stamps
        # Flip one bound pod to Running in place, as the kubelet would.
        job = cache.jobs["ns/pg0"]
        task = next(
            t for t in job.tasks.values()
            if t.status == TaskStatus.BINDING
        )
        old_pod = task.pod
        new_pod = build_pod(
            "ns", task.name, task.node_name, PodPhase.RUNNING,
            build_resource_list(cpu="500m", memory="512Mi"),
            group_name="pg0",
        )
        new_pod.metadata.uid = old_pod.metadata.uid
        cache.update_pod(old_pod, new_pod)
        snap = cache.snapshot()
        assert task.node_name in snap.dirty_nodes_narrow
        assert task.node_name not in snap.dirty_nodes
        assert "ns/pg0" in snap.dirty_jobs_narrow
        # A RESIZED pod (resreq changed) is NOT a pure flip: full-dirty.
        task2 = next(
            t for t in cache.jobs["ns/pg0"].tasks.values()
            if t.status == TaskStatus.RUNNING
        )
        bigger = build_pod(
            "ns", task2.name, task2.node_name, PodPhase.RUNNING,
            build_resource_list(cpu="1000m", memory="512Mi"),
            group_name="pg0",
        )
        bigger.metadata.uid = task2.pod.metadata.uid
        cache.update_pod(task2.pod, bigger)
        snap = cache.snapshot()
        assert task2.node_name in snap.dirty_nodes
        assert task2.node_name not in snap.dirty_nodes_narrow
        cache.shutdown()

    def test_third_party_event_wins_over_narrow(self):
        cache = self._cluster()
        action, _ = get_action("allocate_tpu")
        ssn = open_session(cache, make_tiers(*DEFAULT_TIERS_ARGS))
        action.execute(ssn)
        close_session(ssn)
        assert cache.wait_for_side_effects(timeout=30.0)
        assert cache.wait_for_bookkeeping(timeout=30.0)
        # A watch update on a node that ALSO saw binds: full-dirty wins.
        node = cache.nodes["nn0"]
        cache.update_node(node.node, node.node)
        snap = cache.snapshot()
        assert "nn0" in snap.dirty_nodes
        assert "nn0" not in snap.dirty_nodes_narrow
        cache.shutdown()

    def test_wave_cycle_is_noop_with_wave_patches(self):
        cache = self._cluster()
        action, _ = get_action("allocate_tpu")
        tiers = make_tiers(*DEFAULT_TIERS_ARGS)
        for _ in range(2):
            ssn = open_session(cache, tiers)
            action.execute(ssn)
            close_session(ssn)
            assert cache.wait_for_side_effects(timeout=30.0)
            assert cache.wait_for_bookkeeping(timeout=30.0)
        # Second cycle absorbed the first cycle's placement wave as a
        # warm no-op with allocation-only column patches.
        assert last_stats["warm_outcome"] == "noop"
        ts = {
            k: v for k, v in last_stats.items() if k.startswith("tensorize")
        }
        assert ts.get("tensorize_incremental") is True
        assert ts.get("tensorize_wave_patched", 0) > 0
        assert ts.get("tensorize_wave_patched") == ts.get(
            "tensorize_dirty_nodes"
        )
        cache.shutdown()


class TestCarriedRepin:
    def test_partial_placement_noop_chain_stays_warm(self):
        """A job with a placed head and an unplaceable tail: the wave
        re-mints its clone (narrow), the absorb cycle passes via the
        remainder check, and advance_noop RE-PINS the carried entry —
        the following cycles must stay noop instead of paying one
        spurious carried-changed full solve per placement wave."""
        cache = make_cache()
        cache.add_queue(build_queue("q0", weight=1))
        for j in range(2):
            cache.add_node(build_node(
                f"n{j}", build_resource_list(cpu="4", memory="16Gi"),
            ))
        cache.add_pod_group(build_pod_group(
            "mix", namespace="ns", min_member=1, queue="q0",
        ))
        # Two placeable heads + one tail that fits NO node; names order
        # the tail last under the uid tiebreak so the job-break gates
        # only the tail.
        for i in range(2):
            cache.add_pod(build_pod(
                "ns", f"mix-a{i}", "", PodPhase.PENDING,
                build_resource_list(cpu="500m", memory="512Mi"),
                group_name="mix",
            ))
        cache.add_pod(build_pod(
            "ns", "mix-z-huge", "", PodPhase.PENDING,
            build_resource_list(cpu="64", memory="512Gi"),
            group_name="mix",
        ))
        action, _ = get_action("allocate_tpu")
        tiers = make_tiers(*DEFAULT_TIERS_ARGS)
        outcomes = []
        placed = []
        for _ in range(5):
            ssn = open_session(cache, tiers)
            action.execute(ssn)
            outcomes.append(last_stats.get("warm_outcome"))
            placed.append(last_stats.get("placed", 0))
            close_session(ssn)
            assert cache.wait_for_side_effects(timeout=30.0)
            assert cache.wait_for_bookkeeping(timeout=30.0)
        cache.shutdown()
        assert placed[0] == 2, (placed, outcomes)
        # Cycle 1 absorbs the wave (noop via the narrow remainder
        # check); every later cycle must stay noop — no spurious
        # carried-changed re-solve of the unchanged problem.
        assert outcomes[1:] == ["noop"] * 4, outcomes


class TestMicroCycles:
    def _sched(self, cache):
        from kube_batch_tpu.scheduler import Scheduler

        conf = (
            'actions: "allocate_tpu"\n'
            "tiers:\n"
            "- plugins:\n"
            "  - name: priority\n"
            "  - name: gang\n"
            "  - name: conformance\n"
            "- plugins:\n"
            "  - name: drf\n"
            "  - name: predicates\n"
            "  - name: proportion\n"
            "  - name: nodeorder\n"
        )
        return Scheduler(cache, scheduler_conf=conf)

    def test_micro_places_arrivals_through_warm_path(self):
        cache = TestNarrowLedger._cluster(TestNarrowLedger())
        sched = self._sched(cache)
        sched.run_once()
        assert cache.wait_for_side_effects(timeout=30.0)
        assert cache.wait_for_bookkeeping(timeout=30.0)
        cache.add_pod_group(build_pod_group(
            "pgm", namespace="ns", min_member=2, queue="q0",
        ))
        for i in range(3):
            cache.add_pod(build_pod(
                "ns", f"pgm-p{i}", "", PodPhase.PENDING,
                build_resource_list(cpu="250m", memory="256Mi"),
                group_name="pgm",
            ))
        from kube_batch_tpu.obs import RECORDER

        assert sched.run_micro()
        assert last_stats.get("warm_outcome") == "solve"
        assert last_stats.get("placed") == 3
        rec = RECORDER.snapshot()[-1]
        assert rec["cycle_kind"] == "micro"
        assert cache.wait_for_side_effects(timeout=30.0)
        cache.shutdown()

    def test_micro_places_through_node_churn(self):
        """Third-party node churn used to void the warm plan and defer
        the whole micro cycle; under the congested-regime fold the
        carried verdicts are forced into the subset instead, and the
        new pod still binds within the micro cycle it arrived in."""
        cache = TestNarrowLedger._cluster(TestNarrowLedger())
        sched = self._sched(cache)
        sched.run_once()
        assert cache.wait_for_side_effects(timeout=30.0)
        assert cache.wait_for_bookkeeping(timeout=30.0)
        node = cache.nodes["nn1"]
        cache.update_node(node.node, node.node)
        cache.add_pod_group(build_pod_group(
            "pgd", namespace="ns", min_member=1, queue="q0",
        ))
        cache.add_pod(build_pod(
            "ns", "pgd-p0", "", PodPhase.PENDING,
            build_resource_list(cpu="250m", memory="256Mi"),
            group_name="pgd",
        ))
        assert sched.run_micro()
        assert "micro_deferred" not in last_stats, last_stats
        assert last_stats.get("warm_outcome") in ("solve", "subset")
        assert last_stats.get("placed") == 1
        assert cache.wait_for_side_effects(timeout=30.0)
        cache.shutdown()

    def test_micro_defers_when_warm_cannot_engage(self):
        cache = TestNarrowLedger._cluster(TestNarrowLedger())
        sched = self._sched(cache)
        sched.run_once()
        assert cache.wait_for_side_effects(timeout=30.0)
        assert cache.wait_for_bookkeeping(timeout=30.0)
        # Invalidate the warm state (what a failed commit or an
        # explicit poke does): with no carried verdicts at all the
        # micro cycle must place NOTHING and leave the work to the
        # periodic cycle.
        from kube_batch_tpu.solver import warm

        warm.invalidate(cache)
        cache.add_pod_group(build_pod_group(
            "pgd", namespace="ns", min_member=1, queue="q0",
        ))
        cache.add_pod(build_pod(
            "ns", "pgd-p0", "", PodPhase.PENDING,
            build_resource_list(cpu="250m", memory="256Mi"),
            group_name="pgd",
        ))
        assert sched.run_micro()
        assert last_stats.get("micro_deferred") == "cold"
        assert "placed" not in last_stats
        # The following periodic cycle picks the pod up.
        sched.run_once()
        assert last_stats.get("placed") == 1
        assert cache.wait_for_side_effects(timeout=30.0)
        cache.shutdown()

    def test_deferred_micro_dirt_folds_forward(self):
        """A deferring micro cycle has already DRAINED the cache's
        dirty ledgers through its session; note_deferred must fold
        that dirt (and the consumed snapshot generation) back into the
        warm state, or one defer would strand every following micro
        cycle on ``stale`` until the next periodic solve."""
        cache = TestNarrowLedger._cluster(TestNarrowLedger())
        sched = self._sched(cache)
        sched.run_once()
        assert cache.wait_for_side_effects(timeout=30.0)
        assert cache.wait_for_bookkeeping(timeout=30.0)
        from kube_batch_tpu.solver import warm

        ws = warm.warm_state_of(cache)
        assert ws is not None and ws.valid
        # Force one defer with the warm state still valid (the
        # releasing gate), with a new pod pending.
        ws.has_releasing = True
        cache.add_pod_group(build_pod_group(
            "pgf", namespace="ns", min_member=1, queue="q0",
        ))
        cache.add_pod(build_pod(
            "ns", "pgf-p0", "", PodPhase.PENDING,
            build_resource_list(cpu="250m", memory="256Mi"),
            group_name="pgf",
        ))
        assert sched.run_micro()
        assert last_stats.get("micro_deferred") == "releasing"
        assert "placed" not in last_stats
        # Gate lifts: the NEXT micro cycle must engage and place the
        # pod the deferred cycle drained — not report stale.
        ws.has_releasing = False
        assert sched.run_micro()
        assert "micro_deferred" not in last_stats, last_stats
        assert last_stats.get("warm_outcome") in ("solve", "subset")
        assert last_stats.get("placed") == 1
        assert cache.wait_for_side_effects(timeout=30.0)
        cache.shutdown()

    def test_arrival_listener_fires_on_pending_pod(self):
        cache = TestNarrowLedger._cluster(TestNarrowLedger())
        fired = []
        cache.set_arrival_listener(lambda: fired.append(1))
        cache.add_pod(build_pod(
            "ns", "px", "", PodPhase.PENDING,
            build_resource_list(cpu="100m", memory="64Mi"),
        ))
        assert fired
        # A bound pod (not schedulable work) does not wake the loop.
        fired.clear()
        cache.add_pod(build_pod(
            "ns", "py", "nn0", PodPhase.RUNNING,
            build_resource_list(cpu="100m", memory="64Mi"),
        ))
        assert not fired
        cache.shutdown()


class TestIncrementalSnapshotParity:
    def test_randomized_churn_matches_full_walk(self):
        rng = np.random.RandomState(7)
        driver = _ScenarioDriver(7)
        script = driver.script(
            ["wave", "arrival", "completion", "evict"], 8
        )
        cache = make_cache()
        for q in range(2):
            cache.add_queue(build_queue(f"q{q}", weight=q + 1))
        for j in range(6):
            cache.add_node(build_node(
                f"n{j}", build_resource_list(cpu="8", memory="32Gi"),
            ))
        action, _ = get_action("allocate_tpu")
        tiers = make_tiers(*DEFAULT_TIERS_ARGS)
        d = _ScenarioDriver(7)
        d.nodes = 6
        for events in script:
            d._apply(cache, events)
            # Incremental snapshot vs forced full walk on the SAME
            # mirror state: keys, order, and object identity must agree
            # (identity: both must reuse the same pool clones).
            snap_inc = cache.snapshot()
            prev = _env("KBT_SNAPSHOT_INCREMENTAL", "0")
            snap_full = cache.snapshot()
            _env("KBT_SNAPSHOT_INCREMENTAL", prev)
            assert list(snap_inc.nodes) == list(snap_full.nodes)
            assert list(snap_inc.jobs) == list(snap_full.jobs)
            for k in snap_inc.nodes:
                assert snap_inc.nodes[k] is snap_full.nodes[k]
            for k in snap_inc.jobs:
                assert snap_inc.jobs[k] is snap_full.jobs[k]
            t_inc = snap_inc.total_allocatable
            t_full = snap_full.total_allocatable
            assert abs(t_inc.milli_cpu - t_full.milli_cpu) < 1e-6
            assert abs(t_inc.memory - t_full.memory) < 1.0
            ssn = open_session(cache, tiers)
            action.execute(ssn)
            close_session(ssn)
            assert cache.wait_for_side_effects(timeout=30.0)
            assert cache.wait_for_bookkeeping(timeout=30.0)
        cache.shutdown()

    def test_direct_mirror_poke_is_caught(self):
        """A test (or rogue caller) replacing a mirror object without
        any ledger stamp must still invalidate its snapshot entry —
        the verification arrays, not the ledger, are the truth."""
        cache = make_cache()
        cache.add_queue(build_queue("q0", weight=1))
        cache.add_node(build_node(
            "n0", build_resource_list(cpu="8", memory="32Gi"),
        ))
        snap1 = cache.snapshot()
        # In-place mutation through a mutator (bumps _ver, no stamp).
        from kube_batch_tpu.api import TaskInfo

        pod = build_pod(
            "ns", "poke", "n0", PodPhase.RUNNING,
            build_resource_list(cpu="1", memory="1Gi"),
        )
        with cache.mutex:
            cache.nodes["n0"].add_task(TaskInfo(pod))
        snap2 = cache.snapshot()
        assert snap2.nodes["n0"] is not snap1.nodes["n0"]
        assert snap2.nodes["n0"].idle.milli_cpu == (
            snap1.nodes["n0"].idle.milli_cpu - 1000.0
        )
        cache.shutdown()


class TestMicroVerificationSkip:
    """Micro snapshots (KBT_MICRO_VERIFY=ledger, the r17 default) skip
    the O(n) ``_ver`` compare and verify only ledger-named positions +
    the arrival tail. Pinned here: ledger-named churn IS re-verified on
    the micro path, and an out-of-band poke that bypasses every ledger
    — which nothing in-tree does — is reconciled by the next PERIODIC
    snapshot's full verification, never lost."""

    def _cluster(self):
        cache = make_cache()
        cache.add_queue(build_queue("q0", weight=1))
        cache.add_node(build_node(
            "n0", build_resource_list(cpu="8", memory="32Gi", pods=110),
        ))
        return cache

    def test_ledger_named_churn_verified_on_micro_path(self):
        cache = self._cluster()
        cache.add_pod_group(build_pod_group(
            "pg0", namespace="ns", min_member=1, queue="q0",
        ))
        old_pod = build_pod(
            "ns", "pg0-p0", "n0", PodPhase.RUNNING,
            build_resource_list(cpu="500m", memory="512Mi"),
            group_name="pg0",
        )
        cache.add_pod(old_pod)
        snap0 = cache.snapshot()
        before = cache.snap_ledger_verifies
        # Watch event (pod resize) stamps the dirty ledger: the micro
        # fast verification must re-clone exactly that position.
        bigger = build_pod(
            "ns", "pg0-p0", "n0", PodPhase.RUNNING,
            build_resource_list(cpu="1500m", memory="512Mi"),
            group_name="pg0",
        )
        bigger.metadata.uid = old_pod.metadata.uid
        cache.update_pod(old_pod, bigger)
        snap1 = cache.snapshot(micro=True)
        assert cache.snap_ledger_verifies == before + 1
        assert snap1.nodes["n0"].idle.milli_cpu == (
            snap0.nodes["n0"].idle.milli_cpu - 1000.0
        )
        cache.shutdown()

    def test_out_of_band_poke_reconciled_by_periodic_full(self):
        cache = self._cluster()
        snap0 = cache.snapshot()
        full_before = cache.snap_full_verifies
        # Direct mutator poke: bumps the mirror ``_ver`` but stamps NO
        # ledger — outside every in-tree write path.
        from kube_batch_tpu.api import TaskInfo

        pod = build_pod(
            "ns", "poke", "n0", PodPhase.RUNNING,
            build_resource_list(cpu="1", memory="1Gi"),
        )
        with cache.mutex:
            cache.nodes["n0"].add_task(TaskInfo(pod))
        # The micro snapshot's ledger verification has no name to
        # recheck: it reuses the stale clone (the documented trade).
        snap_micro = cache.snapshot(micro=True)
        assert snap_micro.nodes["n0"].idle.milli_cpu == (
            snap0.nodes["n0"].idle.milli_cpu
        )
        # The periodic snapshot always runs the full compare and
        # reconciles: the reconciliation authority never moved.
        snap_full = cache.snapshot()
        assert cache.snap_full_verifies > full_before
        assert snap_full.nodes["n0"].idle.milli_cpu == (
            snap0.nodes["n0"].idle.milli_cpu - 1000.0
        )
        cache.shutdown()


class TestPluginFoldReuse:
    """Cross-session plugin fold reuse (KBT_FOLD_REUSE, default on):
    drf/proportion per-job fold results persist in the cache's
    ``plugin_fold`` store and only churned jobs re-fold. Pinned:
    placements are bit-identical with the store disabled."""

    def test_fold_reuse_bit_parity(self):
        driver = _ScenarioDriver(31)
        script = driver.script(
            ["wave", "arrival", "completion", "evict"], 10
        )
        states_on, _ = _ScenarioDriver(31).run(script, warm=True)
        prev = _env("KBT_FOLD_REUSE", "0")
        try:
            states_off, _ = _ScenarioDriver(31).run(script, warm=True)
        finally:
            _env("KBT_FOLD_REUSE", prev)
        for c, (a, b) in enumerate(zip(states_on, states_off)):
            assert a == b, f"fold-reuse diverged at cycle {c}"

    def test_fold_store_populated_and_reused(self):
        cache = make_cache()
        cache.add_queue(build_queue("q0", weight=1))
        cache.add_node(build_node(
            "n0", build_resource_list(cpu="8", memory="32Gi", pods=110),
        ))
        cache.add_pod_group(build_pod_group(
            "pg0", namespace="ns", min_member=1, queue="q0",
        ))
        cache.add_pod(build_pod(
            "ns", "pg0-p0", "", PodPhase.PENDING,
            build_resource_list(cpu="500m", memory="512Mi"),
            group_name="pg0",
        ))
        action, _ = get_action("allocate_tpu")
        tiers = make_tiers(*DEFAULT_TIERS_ARGS)
        ssn = open_session(cache, tiers)
        action.execute(ssn)
        close_session(ssn)
        assert cache.wait_for_side_effects(timeout=30.0)
        assert cache.wait_for_bookkeeping(timeout=30.0)
        assert cache.plugin_fold, "fold store empty after a session"
        # Session 2 re-folds the churned job (its pod bound) and pins
        # the settled clone; with NOTHING changing after that, session
        # 3 must reuse the folded attrs wholesale, by identity.
        ssn = open_session(cache, tiers)
        action.execute(ssn)
        close_session(ssn)
        assert cache.wait_for_side_effects(timeout=30.0)
        attrs2 = {
            uid: ent[2]
            for uid, ent in cache.plugin_fold["drf"]["entries"].items()
        }
        assert attrs2, "drf fold entries empty after steady session"
        ssn = open_session(cache, tiers)
        action.execute(ssn)
        close_session(ssn)
        assert cache.wait_for_side_effects(timeout=30.0)
        entries3 = cache.plugin_fold["drf"]["entries"]
        for uid, attr in attrs2.items():
            assert entries3[uid][2] is attr, uid
        cache.shutdown()


class TestWarmRetraceGuard:
    def test_zero_new_jits_on_warm_path(self):
        """Steady warm cycles on the jax backend must not mint solver
        or patch jit variants after the first warm round's shapes are
        compiled (the warm problem reuses the same buckets)."""
        prev = _env("KBT_SOLVER", "jax")
        try:
            from kube_batch_tpu.solver import jit_compilation_count

            cache = make_cache()
            cache.add_queue(build_queue("q0", weight=1))
            for j in range(4):
                cache.add_node(build_node(
                    f"n{j}", build_resource_list(cpu="64", memory="256Gi"),
                ))
            action, _ = get_action("allocate_tpu")
            tiers = make_tiers(*DEFAULT_TIERS_ARGS)

            def burst(r):
                cache.add_pod_group(build_pod_group(
                    f"w{r}", namespace="ns", min_member=1, queue="q0",
                ))
                for i in range(3):
                    cache.add_pod(build_pod(
                        "ns", f"w{r}-p{i}", "", PodPhase.PENDING,
                        build_resource_list(cpu="250m", memory="256Mi"),
                        group_name=f"w{r}",
                    ))

            def cycle():
                ssn = open_session(cache, tiers)
                action.execute(ssn)
                close_session(ssn)
                assert cache.wait_for_side_effects(timeout=30.0)
                assert cache.wait_for_bookkeeping(timeout=30.0)

            # Warm-up: two burst rounds compile every shape bucket the
            # steady stream will use.
            for r in range(2):
                burst(r)
                cycle()
            baseline = jit_compilation_count()
            for r in range(2, 6):
                burst(r)
                cycle()
                assert last_stats.get("warm_outcome") in ("solve", "noop")
            assert jit_compilation_count() == baseline
        finally:
            _env("KBT_SOLVER", prev)
            cache.shutdown()

    def test_zero_new_jits_on_subset_path(self):
        """Congested steady state: the rotating rank-stable subset
        solves must reuse the shape buckets the first subset rounds
        compiled — a carried backlog being re-solved every cycle must
        not mint new jit variants per round, or the micro path's
        latency budget is spent in XLA."""
        prev = _env("KBT_SOLVER", "jax")
        try:
            from kube_batch_tpu.solver import jit_compilation_count

            cache = make_cache()
            cache.add_queue(build_queue("q0", weight=1))
            for j in range(2):
                cache.add_node(build_node(
                    f"n{j}",
                    build_resource_list(cpu="4", memory="16Gi", pods=110),
                ))
            action, _ = get_action("allocate_tpu")
            tiers = make_tiers(*DEFAULT_TIERS_ARGS)

            def burst(r, size, cpu):
                cache.add_pod_group(build_pod_group(
                    f"w{r}", namespace="ns", min_member=1, queue="q0",
                ))
                for i in range(size):
                    cache.add_pod(build_pod(
                        "ns", f"w{r}-p{i}", "", PodPhase.PENDING,
                        build_resource_list(
                            cpu=f"{cpu}m", memory="256Mi"
                        ),
                        group_name=f"w{r}",
                    ))

            def complete(n):
                with cache.mutex:
                    tasks = [
                        t for key in sorted(cache.jobs)
                        for t in cache.jobs[key].tasks.values()
                        if t.status == TaskStatus.BINDING and t.node_name
                    ]
                for t in tasks[:n]:
                    t.pod.status.phase = PodPhase.SUCCEEDED
                    cache.delete_pod(t.pod)

            def cycle():
                ssn = open_session(cache, tiers)
                action.execute(ssn)
                close_session(ssn)
                assert cache.wait_for_side_effects(timeout=30.0)
                assert cache.wait_for_bookkeeping(timeout=30.0)

            # Fill the 8000m cluster and overflow it: a 4-pod carried
            # backlog forms, and every following round completes 2
            # bound pods + lands a 2-pod gang — steady congestion.
            burst(0, 8, 1000)
            burst("ov", 4, 1000)
            cycle()
            # Warm-up rounds compile every bucket the rotation uses.
            for r in range(1, 5):
                complete(2)
                burst(r, 2, 1000)
                cycle()
            baseline = jit_compilation_count()
            for r in range(5, 10):
                complete(2)
                burst(r, 2, 1000)
                cycle()
                assert last_stats.get("warm_outcome") == "subset"
            assert jit_compilation_count() == baseline
        finally:
            _env("KBT_SOLVER", prev)
            cache.shutdown()


class TestMicroSimInvariants:
    def test_micro_sim_run_is_invariant_clean(self):
        from kube_batch_tpu.sim.harness import SimConfig, run_sim

        report, _trace = run_sim(SimConfig(
            cycles=120, seed=13, backend="native", micro_every=3,
            faults="bind:0.05",
        ))
        assert report.cycles == 120
        assert report.violations == []
        assert report.cycle_errors == 0
        assert report.placements > 0
