from .scheduler_conf import (
    DEFAULT_SCHEDULER_CONF,
    PluginOption,
    SchedulerConfiguration,
    Tier,
    apply_plugin_conf_defaults,
    parse_scheduler_conf,
)
