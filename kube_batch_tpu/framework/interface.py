"""Action and Plugin interfaces (reference framework/interface.go:19-40)."""

from __future__ import annotations

from abc import ABC, abstractmethod
from typing import TYPE_CHECKING

if TYPE_CHECKING:
    from .session import Session


class Action(ABC):
    """reference interface.go:19-31"""

    @abstractmethod
    def name(self) -> str: ...

    def initialize(self) -> None:
        return None

    @abstractmethod
    def execute(self, ssn: "Session") -> None: ...

    def un_initialize(self) -> None:
        return None


class Plugin(ABC):
    """reference interface.go:34-40. Plugins never act; they install
    callbacks into the Session during on_session_open."""

    @abstractmethod
    def name(self) -> str: ...

    @abstractmethod
    def on_session_open(self, ssn: "Session") -> None: ...

    def on_session_close(self, ssn: "Session") -> None:
        return None
