"""Pallas fused-bid kernel parity (solver/pallas_kernels.py).

Runs the kernel in interpret mode (CPU) and asserts bit-identical bids
against the reference jnp chain from kernels._solve_round."""

import numpy as np
import jax.numpy as jnp
import pytest

from kube_batch_tpu.solver.kernels import (
    CPU_DIM,
    MEM_DIM,
    _dyn_score_core,
    bid_keys,
    dynamic_scores,
    less_equal,
)
from kube_batch_tpu.solver.pallas_kernels import (
    TILE_T,
    pallas_bid,
    pallas_bid_sparse,
)

try:  # pallas import may be unavailable under the purged CPU harness
    from jax.experimental import pallas as _pl  # noqa: F401
    HAVE_PALLAS = True
except Exception:
    HAVE_PALLAS = False

pytestmark = pytest.mark.skipif(
    not HAVE_PALLAS, reason="pallas unavailable in this jax build"
)


def jnp_reference_bid(task_fit, task_req, task_ok, feas, idle, cap, cap_ok,
                      eps, lr_w, br_w, static_score=None):
    """The jnp chain from kernels._solve_round — THE reference semantics
    every pallas_bid parity check (here and in tools/tpu_validation.py)
    compares against. ``static_score`` mirrors pallas_bid's."""
    T = task_fit.shape[0]
    N = idle.shape[0]
    fits = less_equal(task_fit[:, None, :], idle[None, :, :], eps)
    mask = fits & feas & cap_ok[None, :] & task_ok[:, None]
    score = dynamic_scores(task_req, idle, cap, lr_w, br_w)
    if static_score is not None:
        score = score + static_score
    key = bid_keys(
        score,
        jnp.arange(T, dtype=jnp.int32)[:, None],
        jnp.arange(N, dtype=jnp.int32)[None, :],
    )
    key = jnp.where(mask, key, -1)
    any_feas = jnp.any(mask, axis=1)
    bid = jnp.where(
        any_feas, jnp.argmax(key, axis=1).astype(jnp.int32), N
    )
    return bid, any_feas


def _random_case(seed, T, N, R=3):
    rng = np.random.RandomState(seed)
    task_req = rng.uniform(100, 3000, (T, R)).astype(np.float32)
    task_fit = task_req * rng.uniform(1.0, 1.2, (T, 1)).astype(np.float32)
    idle = rng.uniform(500, 32000, (N, R)).astype(np.float32)
    cap = idle * rng.uniform(1.0, 1.5, (N, 1)).astype(np.float32)
    return dict(
        task_fit=jnp.asarray(task_fit),
        task_req=jnp.asarray(task_req),
        task_ok=jnp.asarray(rng.rand(T) > 0.1),
        feas=jnp.asarray(rng.rand(T, N) > 0.2),
        idle=jnp.asarray(idle),
        cap=jnp.asarray(cap),
        cap_ok=jnp.asarray(rng.rand(N) > 0.1),
        eps=jnp.asarray([10.0] * R, jnp.float32),
        lr_w=jnp.asarray(1.0, jnp.float32),
        br_w=jnp.asarray(1.0, jnp.float32),
    )


def test_pallas_bid_matches_jnp_chain():
    for seed in (0, 1, 2):
        case = _random_case(seed, T=2 * TILE_T, N=256)
        bid_p, any_p = pallas_bid(
            case["task_fit"], case["task_req"], case["task_ok"],
            case["feas"], case["idle"], case["cap"], case["cap_ok"],
            case["eps"], case["lr_w"], case["br_w"], interpret=True,
        )
        bid_j, any_j = jnp_reference_bid(
            case["task_fit"], case["task_req"], case["task_ok"],
            case["feas"], case["idle"], case["cap"], case["cap_ok"],
            case["eps"], case["lr_w"], case["br_w"],
        )
        np.testing.assert_array_equal(np.asarray(any_p), np.asarray(any_j))
        np.testing.assert_array_equal(np.asarray(bid_p), np.asarray(bid_j))


def test_pallas_bid_all_infeasible_column():
    case = _random_case(5, T=TILE_T, N=128)
    case["cap_ok"] = jnp.zeros(128, bool)
    bid_p, any_p = pallas_bid(
        case["task_fit"], case["task_req"], case["task_ok"],
        case["feas"], case["idle"], case["cap"], case["cap_ok"],
        case["eps"], case["lr_w"], case["br_w"], interpret=True,
    )
    assert not bool(np.asarray(any_p).any())
    assert (np.asarray(bid_p) == 128).all()


def test_pallas_bid_with_static_score_rows():
    # Static plugin score rows (node/pod affinity, nodeorder) — the gate
    # previously disabled the fused kernel whenever these existed, i.e.
    # under the STANDARD configuration (VERDICT r3 weakness 2).
    for seed in (3, 4):
        case = _random_case(seed, T=2 * TILE_T, N=256)
        rng = np.random.RandomState(seed + 100)
        static = jnp.asarray(
            rng.uniform(0, 10, (2 * TILE_T, 256)).astype(np.float32)
        )
        bid_p, any_p = pallas_bid(
            case["task_fit"], case["task_req"], case["task_ok"],
            case["feas"], case["idle"], case["cap"], case["cap_ok"],
            case["eps"], case["lr_w"], case["br_w"],
            static_score=static, interpret=True,
        )
        bid_j, any_j = jnp_reference_bid(
            case["task_fit"], case["task_req"], case["task_ok"],
            case["feas"], case["idle"], case["cap"], case["cap_ok"],
            case["eps"], case["lr_w"], case["br_w"], static_score=static,
        )
        np.testing.assert_array_equal(np.asarray(any_p), np.asarray(any_j))
        np.testing.assert_array_equal(np.asarray(bid_p), np.asarray(bid_j))


def jnp_reference_sparse_bid(task_fit, task_req, task_ok, cand_nodes,
                             cand_static, idle, cap, cap_ok, eps,
                             lr_w, br_w):
    """The jnp slab chain from kernels._sparse_round — the reference
    semantics pallas_bid_sparse must reproduce bit-for-bit."""
    T = task_fit.shape[0]
    N = idle.shape[0]
    valid = cand_nodes < N
    safe = jnp.minimum(cand_nodes, N - 1)
    idle_slab = idle[safe]
    fits = less_equal(task_fit[:, None, :], idle_slab, eps)
    mask = fits & valid & cap_ok[safe] & task_ok[:, None]
    dims = (CPU_DIM, MEM_DIM)
    score = _dyn_score_core(
        task_req[:, None, dims], idle_slab[..., dims],
        cap[safe][..., dims], lr_w, br_w,
    ) + cand_static
    key = bid_keys(
        score, jnp.arange(T, dtype=jnp.int32)[:, None], cand_nodes
    )
    key = jnp.where(mask, key, -1)
    any_feas = jnp.any(mask, axis=1)
    col = jnp.argmax(key, axis=1)
    bid = cand_nodes[jnp.arange(T), col]
    return jnp.where(any_feas, bid, N), any_feas


def _sparse_case(seed, T, N, K, R=3):
    case = _random_case(seed, T, N, R)
    rng = np.random.RandomState(seed + 1000)
    cand = np.argsort(rng.rand(T, N), axis=1)[:, :K].astype(np.int32)
    cand[rng.rand(T, K) < 0.15] = N  # padding sentinels
    cand.sort(axis=1)                # ascending, sentinels last
    case["cand_nodes"] = jnp.asarray(cand)
    case["cand_static"] = jnp.asarray(
        rng.uniform(0, 5, (T, K)).astype(np.float32)
    )
    del case["feas"]
    return case


def test_pallas_sparse_bid_matches_jnp_chain():
    for seed, K in ((0, 8), (1, 16), (2, 4)):
        case = _sparse_case(seed, T=2 * TILE_T, N=256, K=K)
        args = (
            case["task_fit"], case["task_req"], case["task_ok"],
            case["cand_nodes"], case["cand_static"], case["idle"],
            case["cap"], case["cap_ok"], case["eps"], case["lr_w"],
            case["br_w"],
        )
        bid_p, any_p = pallas_bid_sparse(*args, interpret=True)
        bid_j, any_j = jnp_reference_sparse_bid(*args)
        np.testing.assert_array_equal(np.asarray(any_p), np.asarray(any_j))
        np.testing.assert_array_equal(np.asarray(bid_p), np.asarray(bid_j))


def test_pallas_sparse_bid_all_padded_row():
    # A task whose slab is all sentinels must report no feasible bid.
    case = _sparse_case(5, T=TILE_T, N=128, K=8)
    cand = np.asarray(case["cand_nodes"]).copy()
    cand[0] = 128
    case["cand_nodes"] = jnp.asarray(cand)
    bid_p, any_p = pallas_bid_sparse(
        case["task_fit"], case["task_req"], case["task_ok"],
        case["cand_nodes"], case["cand_static"], case["idle"],
        case["cap"], case["cap_ok"], case["eps"], case["lr_w"],
        case["br_w"], interpret=True,
    )
    assert not bool(np.asarray(any_p)[0])
    assert int(np.asarray(bid_p)[0]) == 128


def test_pallas_bid_unaligned_task_axis():
    # T not a multiple of TILE_T: the kernel pads internally and slices
    # the outputs back; padded rows must never influence real rows.
    # Includes static score rows so the unaligned+static combination —
    # production's standard shape — is covered, not just each alone.
    for T in (TILE_T - 27, TILE_T + 1, 3 * TILE_T - 64):
        case = _random_case(11, T=T, N=128)
        for static in (
            None,
            jnp.asarray(np.random.RandomState(T).uniform(
                0, 10, (T, 128)).astype(np.float32)),
        ):
            bid_p, any_p = pallas_bid(
                case["task_fit"], case["task_req"], case["task_ok"],
                case["feas"], case["idle"], case["cap"], case["cap_ok"],
                case["eps"], case["lr_w"], case["br_w"],
                static_score=static, interpret=True,
            )
            bid_j, any_j = jnp_reference_bid(
                case["task_fit"], case["task_req"], case["task_ok"],
                case["feas"], case["idle"], case["cap"], case["cap_ok"],
                case["eps"], case["lr_w"], case["br_w"],
                static_score=static,
            )
            assert bid_p.shape == (T,)
            np.testing.assert_array_equal(
                np.asarray(any_p), np.asarray(any_j))
            np.testing.assert_array_equal(
                np.asarray(bid_p), np.asarray(bid_j))
