"""Scheduler policy configuration.

Mirrors reference pkg/scheduler/conf/scheduler_conf.go (:20
SchedulerConfiguration, :28 Tier, :33 PluginOption with per-callback enable
flags :36-55) and the YAML policy format of config/kube-batch-conf.yaml:

    actions: "allocate, backfill"
    tiers:
    - plugins:
      - name: priority
      - name: gang
    - plugins:
      - name: drf
      - name: predicates
      - name: proportion
      - name: nodeorder

Per-plugin defaults are all-on (reference plugins/defaults.go:23
ApplyPluginConfDefaults).
"""

from __future__ import annotations

from dataclasses import dataclass, field
from typing import Dict, List, Optional

import yaml


@dataclass
class PluginOption:
    """reference scheduler_conf.go:33-57"""

    name: str
    enabled_job_order: Optional[bool] = None
    enabled_job_ready: Optional[bool] = None
    enabled_job_pipelined: Optional[bool] = None
    enabled_task_order: Optional[bool] = None
    enabled_preemptable: Optional[bool] = None
    enabled_reclaimable: Optional[bool] = None
    enabled_queue_order: Optional[bool] = None
    enabled_predicate: Optional[bool] = None
    enabled_node_order: Optional[bool] = None
    arguments: Dict[str, str] = field(default_factory=dict)


@dataclass
class Tier:
    """reference scheduler_conf.go:28-31"""

    plugins: List[PluginOption] = field(default_factory=list)


@dataclass
class SchedulerConfiguration:
    """reference scheduler_conf.go:20-26"""

    actions: str = ""
    tiers: List[Tier] = field(default_factory=list)


# Reference-compatible enable keys (scheduler_conf.go:37-54 yaml tags).
_ENABLE_FIELDS = {
    "enableJobOrder": "enabled_job_order",
    "enableJobReady": "enabled_job_ready",
    "enableJobPipelined": "enabled_job_pipelined",
    "enableTaskOrder": "enabled_task_order",
    "enablePreemptable": "enabled_preemptable",
    "enableReclaimable": "enabled_reclaimable",
    "enableQueueOrder": "enabled_queue_order",
    "enablePredicate": "enabled_predicate",
    "enableNodeOrder": "enabled_node_order",
}
# Alias spelling: <fn>Disabled: true ≡ enable<Fn>: false.
_DISABLE_FIELDS = {
    "jobOrderDisabled": "enabled_job_order",
    "jobReadyDisabled": "enabled_job_ready",
    "jobPipelinedDisabled": "enabled_job_pipelined",
    "taskOrderDisabled": "enabled_task_order",
    "preemptableDisabled": "enabled_preemptable",
    "reclaimableDisabled": "enabled_reclaimable",
    "queueOrderDisabled": "enabled_queue_order",
    "predicateDisabled": "enabled_predicate",
    "nodeOrderDisabled": "enabled_node_order",
}


def apply_plugin_conf_defaults(option: PluginOption) -> None:
    """Everything defaults to enabled (reference plugins/defaults.go:23-52)."""
    for attr in (
        "enabled_job_order",
        "enabled_job_ready",
        "enabled_job_pipelined",
        "enabled_task_order",
        "enabled_preemptable",
        "enabled_reclaimable",
        "enabled_queue_order",
        "enabled_predicate",
        "enabled_node_order",
    ):
        if getattr(option, attr) is None:
            setattr(option, attr, True)


def parse_scheduler_conf(confstr: str) -> SchedulerConfiguration:
    """Parse YAML policy (reference scheduler/util.go:44-72 loadSchedulerConf).

    Accepts the reference YAML schema: plugin entries carry ``name``, optional
    ``*Disabled`` booleans, and free-form string ``arguments``.
    """
    data = yaml.safe_load(confstr) or {}
    conf = SchedulerConfiguration(actions=data.get("actions", ""))
    for tier_data in data.get("tiers", []) or []:
        tier = Tier()
        for p in tier_data.get("plugins", []) or []:
            opt = PluginOption(name=p["name"])
            for yaml_key, attr in _ENABLE_FIELDS.items():
                if yaml_key in p:
                    setattr(opt, attr, bool(p[yaml_key]))
            for yaml_key, attr in _DISABLE_FIELDS.items():
                if yaml_key in p:
                    setattr(opt, attr, not bool(p[yaml_key]))
            raw_args = p.get("arguments") or {}
            opt.arguments = {str(k): str(v) for k, v in raw_args.items()}
            apply_plugin_conf_defaults(opt)
            tier.plugins.append(opt)
        conf.tiers.append(tier)
    return conf


# Default policy (reference scheduler/util.go:32-42 defaultSchedulerConf).
DEFAULT_SCHEDULER_CONF = """
actions: "allocate, backfill"
tiers:
- plugins:
  - name: priority
  - name: gang
- plugins:
  - name: drf
  - name: predicates
  - name: proportion
  - name: nodeorder
"""
