"""Plugin argument parsing (reference framework/arguments.go:27-78)."""

from __future__ import annotations

from typing import Dict, Optional


class Arguments(Dict[str, str]):
    """String map with typed getters; getters leave the default untouched on
    missing/blank/invalid values (reference arguments.go:32-56)."""

    def get_int(self, key: str, default: Optional[int] = None) -> Optional[int]:
        value = self.get(key, "")
        if not value.strip():
            return default
        try:
            return int(value)
        except ValueError:
            return default

    def get_float(self, key: str, default: Optional[float] = None) -> Optional[float]:
        value = self.get(key, "")
        if not value.strip():
            return default
        try:
            return float(value)
        except ValueError:
            return default

    def get_bool(self, key: str, default: Optional[bool] = None) -> Optional[bool]:
        value = self.get(key, "").strip().lower()
        if not value:
            return default
        if value in ("true", "1", "yes"):
            return True
        if value in ("false", "0", "no"):
            return False
        return default
