"""Device-resident snapshot parity: the patched resident buffers must
be BIT-IDENTICAL to a fresh cold pack of the same snapshot, across
randomized churn and through every fallback path (bucket growth, layout
change, bulk dirtiness) — and the solver must produce identical results
on either.

The contract under test (solver/device_cache.py): a field either
reuses its resident buffer (host arrays identical), scatter-patches the
dirty rows (donated in-place update), or re-uploads whole; whichever
path ran, ``np.asarray(device buffer) == host array`` exactly.
"""

import numpy as np
import pytest

import kube_batch_tpu.actions  # noqa: F401 (registers actions)
import kube_batch_tpu.plugins  # noqa: F401 (registers plugins)
from kube_batch_tpu.api import PodPhase, build_resource_list
from kube_batch_tpu.framework import close_session, open_session
from kube_batch_tpu.solver import PackedInputs, solve_jit, tensorize
from kube_batch_tpu.solver.device_cache import last_pack_stats
from kube_batch_tpu.utils.test_utils import build_pod, build_pod_group

from tests.actions.test_actions import DEFAULT_TIERS_ARGS, make_tiers
from tests.unit.test_cycle_pipeline import build_cluster, session_pairs


def drop_device_cache(cache):
    if hasattr(cache, "_device_snapshot_cache"):
        delattr(cache, "_device_snapshot_cache")


def snapshot_fields(inputs):
    """Host copies of every PackedInputs buffer, taken IMMEDIATELY (a
    later patch donates and deletes resident buffers)."""
    return {f: np.asarray(getattr(inputs, f)) for f in inputs._fields}


def pack_twice_and_compare(ssn):
    """Pack via the resident cache, then via a fresh cold cache, and
    require bit-identical buffers. Returns the cached-path pack stats.
    The fresh pack REPLACES the device cache, so the next cycle patches
    against known-good state (continuity stays exercised)."""
    inputs_cached, ctx = tensorize(ssn)
    if inputs_cached is None:
        drop_device_cache(ssn.cache)
        inputs_fresh, _ = tensorize(ssn)
        assert inputs_fresh is None
        return None
    cached = snapshot_fields(inputs_cached)
    stats = dict(last_pack_stats)
    drop_device_cache(ssn.cache)
    inputs_fresh, _ = tensorize(ssn)
    assert dict(last_pack_stats)["uploads"] == len(PackedInputs._fields)
    fresh = snapshot_fields(inputs_fresh)
    for name in PackedInputs._fields:
        np.testing.assert_array_equal(
            cached[name], fresh[name],
            err_msg=f"device-patched vs fresh pack mismatch in {name}",
        )
    return stats


class TestDeviceCacheParity:
    def test_randomized_churn_parity(self):
        rng = np.random.RandomState(17)
        c = build_cluster(seed=17, groups=8, per_group=6, nodes=8)
        tiers = make_tiers(*DEFAULT_TIERS_ARGS)
        saw_patch = saw_reuse = False
        extra = 0
        for cycle in range(8):
            ssn = open_session(c, tiers)
            stats = pack_twice_and_compare(ssn)
            if stats is not None:
                saw_patch = saw_patch or stats["patches"] > 0
                saw_reuse = saw_reuse or stats["reuses"] > 0
            # Churn: place a random subset, plus new arrivals every
            # other cycle (same protocol as the tensorize parity test).
            pairs = session_pairs(ssn)
            if pairs:
                take = rng.randint(1, min(6, len(pairs)) + 1)
                idx = rng.choice(len(pairs), size=take, replace=False)
                ssn.allocate_batch([pairs[i] for i in sorted(idx)])
            assert c.wait_for_side_effects()
            assert c.wait_for_bookkeeping()
            close_session(ssn)
            if cycle % 2 == 0:
                g = f"pgx{extra}"
                extra += 1
                c.add_pod_group(build_pod_group(
                    g, namespace="ns", min_member=1, queue="q0"
                ))
                for i in range(int(rng.randint(1, 4))):
                    c.add_pod(build_pod(
                        "ns", f"{g}-p{i}", "", PodPhase.PENDING,
                        build_resource_list(
                            cpu=f"{int(rng.choice([250, 500]))}m",
                            memory="256Mi",
                        ),
                        group_name=g,
                    ))
        # The loop must have exercised the interesting paths, not just
        # cold uploads.
        assert saw_patch and saw_reuse
        c.shutdown()

    def test_solver_results_bit_exact_on_patched_inputs(self):
        """Solve on device-patched buffers == solve on a fresh pack."""
        c = build_cluster(seed=23)
        tiers = make_tiers(*DEFAULT_TIERS_ARGS)
        ssn = open_session(c, tiers)
        tensorize(ssn)  # cold pack -> resident buffers
        # Churn a couple of placements so the next pack patches.
        pairs = session_pairs(ssn)
        ssn.allocate_batch(pairs[:3])
        assert c.wait_for_side_effects()
        assert c.wait_for_bookkeeping()
        close_session(ssn)

        ssn = open_session(c, tiers)
        inputs_cached, _ = tensorize(ssn)
        r_cached = solve_jit(inputs_cached)
        a_cached = np.asarray(r_cached.assigned)
        drop_device_cache(c)
        inputs_fresh, _ = tensorize(ssn)
        r_fresh = solve_jit(inputs_fresh)
        np.testing.assert_array_equal(
            a_cached, np.asarray(r_fresh.assigned)
        )
        close_session(ssn)
        c.shutdown()

    def test_steady_cycle_zero_uploads(self):
        """An unchanged snapshot reuses every resident buffer: zero
        host->device bytes shipped."""
        c = build_cluster(seed=29)
        tiers = make_tiers(*DEFAULT_TIERS_ARGS)
        ssn = open_session(c, tiers)
        tensorize(ssn)  # cold
        inputs, _ = tensorize(ssn)  # identical snapshot
        assert inputs is not None
        stats = dict(last_pack_stats)
        assert stats["uploads"] == 0
        assert stats["patches"] == 0
        assert stats["bytes_shipped"] == 0
        assert stats["reuses"] == len(PackedInputs._fields)
        close_session(ssn)
        c.shutdown()

    def test_bucket_growth_falls_back_to_full_upload(self):
        """Crossing a task-shape bucket changes buffer shapes; the task
        fields must re-upload (reason: shape-change) and stay exact."""
        c = build_cluster(seed=31, groups=6, per_group=8)  # 48 tasks
        tiers = make_tiers(*DEFAULT_TIERS_ARGS)
        ssn = open_session(c, tiers)
        inputs, _ = tensorize(ssn)
        assert inputs.task_f32.shape[1] == 256  # bucket floor
        close_session(ssn)
        # Grow past the 256 bucket.
        c.add_pod_group(build_pod_group(
            "pgrow", namespace="ns", min_member=1, queue="q0"
        ))
        for i in range(240):
            c.add_pod(build_pod(
                "ns", f"pgrow-p{i}", "", PodPhase.PENDING,
                build_resource_list(cpu="250m", memory="256Mi"),
                group_name="pgrow",
            ))
        ssn = open_session(c, tiers)
        stats = pack_twice_and_compare(ssn)
        assert stats["full_reasons"].get("task_f32") == "shape-change"
        assert stats["full_reasons"].get("task_i32") == "shape-change"
        close_session(ssn)
        c.shutdown()

    def test_layout_change_falls_back_to_full_upload(self):
        """A new scalar resource grows the resource dim R; every
        R-bearing buffer re-uploads and stays exact."""
        c = build_cluster(seed=37)
        tiers = make_tiers(*DEFAULT_TIERS_ARGS)
        ssn = open_session(c, tiers)
        tensorize(ssn)
        close_session(ssn)
        c.add_pod_group(build_pod_group(
            "pgpu", namespace="ns", min_member=1, queue="q0"
        ))
        c.add_pod(build_pod(
            "ns", "pgpu-p0", "", PodPhase.PENDING,
            build_resource_list(cpu="500m", memory="256Mi",
                                **{"nvidia.com/gpu": 1}),
            group_name="pgpu",
        ))
        ssn = open_session(c, tiers)
        stats = pack_twice_and_compare(ssn)
        for f in ("task_f32", "node_f32", "queue_f32", "misc"):
            assert stats["full_reasons"].get(f) == "shape-change", f
        close_session(ssn)
        c.shutdown()

    def test_pack_ownership_is_cache_scoped(self):
        """A later patch donates the prior cycle's buffer: holding
        PackedInputs across packs on the same scheduler cache is a
        documented ownership violation, pinned here so the rule never
        silently changes."""
        c = build_cluster(seed=41)
        tiers = make_tiers(*DEFAULT_TIERS_ARGS)
        ssn = open_session(c, tiers)
        inputs0, _ = tensorize(ssn)
        held = {f: getattr(inputs0, f) for f in inputs0._fields}
        pairs = session_pairs(ssn)
        ssn.allocate_batch(pairs[:2])
        assert c.wait_for_side_effects()
        assert c.wait_for_bookkeeping()
        close_session(ssn)
        ssn = open_session(c, tiers)
        inputs1, _ = tensorize(ssn)
        stats = dict(last_pack_stats)
        close_session(ssn)
        patched = [
            f for f, o in stats["field_outcomes"].items() if o == "patch"
        ]
        if not patched:
            pytest.skip("churn produced no patch on this backend")
        # The donated buffers are deleted; the fresh ones are intact.
        for f in patched:
            with pytest.raises(RuntimeError):
                np.asarray(held[f]) + 0
            assert np.asarray(getattr(inputs1, f)).shape == held[f].shape
        c.shutdown()
