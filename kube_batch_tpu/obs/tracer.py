"""Hierarchical span tracer with Chrome trace-event export.

Design constraints (ISSUE 5 tentpole):

- **Low overhead.** A disabled ``span()`` is one attribute read, one
  bool test, and a shared no-op context manager — no allocation, no
  clock read. An enabled span costs two ``perf_counter`` reads, one
  small dict, and one lock-free ``deque.append``. The bench's ``obs``
  section pins the enabled overhead against idle cycles.
- **Thread-aware hierarchy.** Each thread keeps its own span stack
  (``threading.local``), so spans opened on the overlap window's worker
  threads (native solve worker, cache side-effect pool, tensorize
  chunk pool) nest correctly. Cross-thread parentage — a worker span
  belonging to the scheduler thread's cycle — uses an explicit capture/
  adopt handshake: the submitting thread calls :meth:`Tracer.capture`
  and the worker wraps its work in ``with TRACER.adopt(token):``.
- **True concurrency in the export.** Events are Chrome trace "X"
  (complete) events keyed by real thread id, so Perfetto renders the
  overlapped solve/apply window as concurrent tracks; ``args`` carry
  the owning cycle and parent span id for programmatic assertions.

``KBT_TRACE_DIR`` enables tracing process-wide (the scheduler loop and
the guarded error path export there); bench ``--trace`` and sim
``--trace-out`` enable it explicitly for one run. ``KBT_TRACE_JAX=1``
additionally wraps solver-stage spans in
``jax.profiler.TraceAnnotation`` so they show up inside XLA profiles.
"""

from __future__ import annotations

import itertools
import json
import os
import threading
import time
from collections import deque
from typing import Optional

TRACE_DIR_ENV = "KBT_TRACE_DIR"
TRACE_JAX_ENV = "KBT_TRACE_JAX"
# Ring bound on buffered events: a week-long scheduler run with tracing
# left on must stay at a fixed memory footprint (oldest spans drop, the
# `dropped` stat records how many).
DEFAULT_CAPACITY = 200_000


def trace_dir_from_env() -> Optional[str]:
    """The process-wide trace directory, or None when tracing is off."""
    return os.environ.get(TRACE_DIR_ENV) or None


class _NullSpan:
    """Shared no-op context manager for the disabled path."""

    __slots__ = ()

    def __enter__(self):
        return self

    def __exit__(self, *exc):
        return False


_NULL = _NullSpan()


# Sentinel distinguishing "no adopted cycle override" from an adopted
# cycle that is legitimately None.
_UNSET = object()


class _Span:
    __slots__ = (
        "tracer", "name", "args", "sid", "parent", "cycle", "t0",
        "_jax_ctx",
    )

    def __init__(self, tracer: "Tracer", name: str, args, jax_annotate):
        self.tracer = tracer
        self.name = name
        self.args = args
        self._jax_ctx = None
        if jax_annotate and tracer.jax_annotations:
            try:
                import jax

                self._jax_ctx = jax.profiler.TraceAnnotation(name)
            except Exception:  # pragma: no cover - jax absent/old
                self._jax_ctx = None

    def __enter__(self):
        t = self.tracer
        tls = t._tls
        stack = getattr(tls, "stack", None)
        if stack is None:
            stack = tls.stack = []
        self.sid = next(t._ids)
        self.parent = (
            stack[-1] if stack else getattr(tls, "adopted", 0)
        )
        # Owning cycle, resolved at ENTRY: an adopted worker span (and
        # anything nested under it) belongs to the cycle that queued
        # it, even when the scheduler thread has already advanced the
        # global cycle counter by the time the worker drains.
        override = getattr(tls, "adopted_cycle", _UNSET)
        self.cycle = t.cycle if override is _UNSET else override
        stack.append(self.sid)
        if self._jax_ctx is not None:
            self._jax_ctx.__enter__()
        self.t0 = time.perf_counter()
        return self

    def __exit__(self, *exc):
        t1 = time.perf_counter()
        t = self.tracer
        if self._jax_ctx is not None:
            self._jax_ctx.__exit__(*exc)
        stack = t._tls.stack
        if stack and stack[-1] == self.sid:
            stack.pop()
        t._record(
            self.name, self.t0, t1, self.sid, self.parent, self.cycle,
            self.args,
        )
        return False


class _Adopt:
    """Context manager installing a cross-thread parent span id (and
    the owning cycle) captured by :meth:`Tracer.capture`."""

    __slots__ = ("tracer", "token", "_prev", "_prev_cycle")

    def __init__(self, tracer: "Tracer", token):
        self.tracer = tracer
        self.token = token

    def __enter__(self):
        tls = self.tracer._tls
        self._prev = getattr(tls, "adopted", 0)
        self._prev_cycle = getattr(tls, "adopted_cycle", _UNSET)
        token = self.token
        if isinstance(token, tuple):
            sid, cycle = token
        else:
            # Back-compat: a bare span id adopts the live cycle.
            sid, cycle = token, _UNSET
        tls.adopted = sid or 0
        tls.adopted_cycle = cycle
        return self

    def __exit__(self, *exc):
        tls = self.tracer._tls
        tls.adopted = self._prev
        tls.adopted_cycle = self._prev_cycle
        return False


class Tracer:
    def __init__(self, capacity: int = DEFAULT_CAPACITY):
        self.enabled = False
        self.capacity = capacity
        self.cycle = None              # stamped by the scheduler loop
        self.annotator = None          # e.g. the sim's virtual-time stamp
        self.jax_annotations = os.environ.get(TRACE_JAX_ENV) == "1"
        self.spans_recorded = 0
        self._events: deque = deque(maxlen=capacity)
        self._thread_names: dict = {}
        self._tls = threading.local()
        self._ids = itertools.count(1)  # count().__next__ is atomic
        self._epoch = time.perf_counter()
        self._pid = os.getpid()

    # -- lifecycle ----------------------------------------------------------

    def enable(self, capacity: Optional[int] = None) -> None:
        if capacity is not None and capacity != self.capacity:
            self.capacity = capacity
            self._events = deque(self._events, maxlen=capacity)
        self.enabled = True

    def disable(self) -> None:
        self.enabled = False

    def reset(self) -> None:
        """Drop buffered events and stats (keeps enabled state).
        Thread names are kept: threads cache their tid in TLS and
        register the name only once, so clearing the map would leave
        later exports without thread_name metadata."""
        self._events.clear()
        self.spans_recorded = 0
        self.cycle = None

    # -- spans --------------------------------------------------------------

    def span(self, name: str, jax_annotate: bool = False, **args):
        if not self.enabled:
            return _NULL
        return _Span(self, name, args or None, jax_annotate)

    def begin_cycle(self, cycle) -> None:
        """Stamp the cycle id every subsequent span's args carry (worker
        threads included, via capture/adopt)."""
        self.cycle = cycle

    def _record(self, name, t0, t1, sid, parent, cycle, span_args) -> None:
        """Shared recording tail of ``_Span.__exit__`` and
        :meth:`complete`: annotator resolution, TLS-cached tid (the
        current_thread().name lookup costs microseconds and only needs
        to run once per thread), and the flat-tuple append — deque
        appends are atomic, so the hot path takes no lock; the Chrome
        event dicts are built at export time."""
        extra = self.annotator
        if extra is not None:
            try:
                extra = extra()
            except Exception:  # pragma: no cover - annotator bug
                extra = None
        tls = self._tls
        tid = getattr(tls, "tid", None)
        if tid is None:
            tid = tls.tid = threading.get_ident()
            self._thread_names[tid] = threading.current_thread().name
        self._events.append((
            name, t0, t1, tid, sid, parent, cycle, span_args, extra,
        ))
        self.spans_recorded += 1

    def complete(self, name: str, t0: float, t1: Optional[float] = None,
                 **args) -> None:
        """Record an already-timed interval as a span — for phases whose
        begin/end are measured with explicit ``perf_counter`` reads
        (the allocate_tpu apply/epilogue blocks). The current thread's
        innermost open span is taken as the parent."""
        if not self.enabled:
            return
        if t1 is None:
            t1 = time.perf_counter()
        tls = self._tls
        stack = getattr(tls, "stack", None)
        parent = stack[-1] if stack else getattr(tls, "adopted", 0)
        override = getattr(tls, "adopted_cycle", _UNSET)
        cycle = self.cycle if override is _UNSET else override
        self._record(name, t0, t1, next(self._ids), parent, cycle,
                     args or None)

    def capture(self):
        """Opaque token — (current span id, owning cycle) of THIS
        thread — for a worker to ``adopt`` so its spans nest under the
        submitting span AND keep the submitting cycle's stamp even when
        they drain after the scheduler thread advanced the counter
        (async binds deliberately drain in the NEXT cycle's overlap
        window)."""
        tls = self._tls
        override = getattr(tls, "adopted_cycle", _UNSET)
        cycle = self.cycle if override is _UNSET else override
        stack = getattr(tls, "stack", None)
        if stack:
            return (stack[-1], cycle)
        return (getattr(tls, "adopted", 0), cycle)

    def adopt(self, token) -> _Adopt:
        return _Adopt(self, token)

    # -- export -------------------------------------------------------------

    def _to_event(self, rec) -> dict:
        name, t0, t1, tid, sid, parent, cycle, span_args, extra = rec
        args = {"sid": sid, "parent": parent, "cycle": cycle}
        if span_args:
            args.update(span_args)
        if extra:
            args.update(extra)
        return {
            "name": name,
            "ph": "X",
            "ts": (t0 - self._epoch) * 1e6,
            "dur": (t1 - t0) * 1e6,
            "pid": self._pid,
            "tid": tid,
            "args": args,
        }

    def events(self) -> list:
        """Buffered spans as Chrome trace-event dicts (built lazily —
        the recording hot path stores flat tuples)."""
        return [self._to_event(rec) for rec in list(self._events)]

    @property
    def dropped(self) -> int:
        return max(0, self.spans_recorded - len(self._events))

    def export(self, path: str) -> str:
        """Write the buffered spans as a Chrome trace-event JSON file
        (load in Perfetto / chrome://tracing). Returns the path."""
        events = self.events()
        meta = [
            {
                "name": "thread_name",
                "ph": "M",
                "pid": self._pid,
                "tid": tid,
                "args": {"name": name},
            }
            for tid, name in sorted(self._thread_names.items())
        ]
        os.makedirs(os.path.dirname(os.path.abspath(path)), exist_ok=True)
        with open(path, "w") as f:
            json.dump(
                {"traceEvents": meta + events, "displayTimeUnit": "ms"},
                f,
            )
        return path


TRACER = Tracer()


def span(name: str, jax_annotate: bool = False, **args):
    """Module-level convenience: ``with obs.span("solve"): ...``."""
    t = TRACER
    if not t.enabled:
        return _NULL
    return _Span(t, name, args or None, jax_annotate)


def export_trace(path: Optional[str] = None, tag: str = "trace") -> Optional[str]:
    """Export the global tracer's buffer.

    With an explicit ``path``, write there. Otherwise write
    ``<KBT_TRACE_DIR>/<tag>-<pid>.json`` when the env dir is set, else
    do nothing (returns None)."""
    if path is None:
        trace_dir = trace_dir_from_env()
        if trace_dir is None:
            return None
        path = os.path.join(trace_dir, f"{tag}-{os.getpid()}.json")
    return TRACER.export(path)


def maybe_enable_from_env() -> bool:
    """Enable the global tracer iff ``KBT_TRACE_DIR`` is set (called by
    the scheduler/server startup paths). Returns the enabled state."""
    if trace_dir_from_env() is not None:
        TRACER.enable()
    return TRACER.enabled
