"""Device-resident selection (solver/select_device.py): bit-equality
against the host topk pass under seeded churn, the labeled host
fallbacks, and layout-token invalidation of the resident key matrix.

The parity loop runs in-process on the conftest 8-device mesh (where
the class-axis sharding of the key matrix engages) and in SUBPROCESSES
on forced 1- and 2-device meshes (the host device count is frozen at
backend init) — the device path must be bit-equal to the host path on
every mesh size, not just the one the suite happens to run on.
"""

import os
import subprocess
import sys

import numpy as np
import pytest


def run_parity_cycles(cycles=5, seed=3, n=700, t=300, groups=8):
    """Seeded churned host-vs-device selection parity loop: every cycle
    asserts the device CandidateSet is bit-equal to the host one (slabs
    AND stats that feed the solver), then churns ~5% of nodes. Also
    asserts the cross-cycle caches on both sides made the SAME reuse
    decisions (the O(churn) warm property survives the port).
    Importable from the small-mesh subprocess scripts; returns the
    total device cache hits so callers can assert warmth engaged."""
    from kube_batch_tpu.solver import select_device
    from kube_batch_tpu.solver.masks import CombinedMask
    from kube_batch_tpu.solver.topk import select_candidates

    rng = np.random.RandomState(seed)
    task_req = np.c_[
        rng.choice([250, 500, 1000, 2000], t),
        rng.choice([256, 1024, 4096], t),
    ].astype(np.float32)
    task_group = (np.arange(t) % groups).astype(np.int32)
    group_rows = rng.rand(groups, n) > 0.1
    pair_idx = np.asarray([5, 17], np.int32)
    pair_rows = rng.rand(2, n) > 0.3
    score_rows_map = {31: (rng.rand(n) * 3.0).astype(np.float32)}
    node_idle = np.c_[
        rng.uniform(4000, 32000, n), rng.uniform(8192, 131072, n)
    ].astype(np.float32)
    node_cap = (node_idle * 1.5).astype(np.float32)
    node_task_count = rng.randint(0, 5, n).astype(np.int32)
    node_max_tasks = np.where(rng.rand(n) < 0.2, 4, 0).astype(np.int32)
    node_ok = rng.rand(n) > 0.05
    eps = np.asarray([10.0, 10.0], np.float32)
    ids = np.arange(n, dtype=np.int64)
    vers = np.zeros(n, np.int64)
    zeros = np.zeros_like(node_idle)
    k = 64

    class _Holder:
        pass

    host_holder = _Holder()
    engine_holder = _Holder()  # device engine rides across cycles
    hits_host = hits_dev = 0
    for _cyc in range(cycles):
        mask = CombinedMask(
            node_ok=node_ok, task_group=task_group,
            group_rows=group_rows & node_ok[None, :],
            pair_idx=pair_idx,
            pair_rows=pair_rows & node_ok[None, :],
        )
        args = (
            mask, score_rows_map, task_req, task_req, node_idle,
            node_cap, zeros, node_task_count, node_max_tasks,
            eps, 1.0, 0.5, k,
        )
        host = select_candidates(
            *args, cache_holder=host_holder,
            node_fp=(ids, vers.copy(), None),
        )
        state = select_device.standalone_state(
            node_idle, node_cap, node_task_count, node_max_tasks,
            node_ok, mask.group_rows,
        )
        state.holder = engine_holder  # production engine residency
        dev = select_candidates(
            *args, cache_holder=_Holder(),
            node_fp=(ids, vers.copy(), None), device_state=state,
        )
        assert host is not None and dev is not None
        assert dev.stats["select_path"] == "device", dev.stats
        assert (dev.cand_idx == host.cand_idx).all()
        assert (dev.cand_static == host.cand_static).all()
        assert (dev.cand_info == host.cand_info).all()
        assert (dev.task_cand == host.task_cand).all()
        assert dev.stats["sel_cache_hits"] == host.stats["sel_cache_hits"]
        hits_host += host.stats["sel_cache_hits"]
        hits_dev += dev.stats["sel_cache_hits"]
        # ~5% node churn (capacity AND task-count moves) before the
        # next cycle; version bumps are how production reports it.
        churn = rng.choice(n, size=max(n // 20, 1), replace=False)
        node_idle[churn] = np.c_[
            rng.uniform(4000, 32000, len(churn)),
            rng.uniform(8192, 131072, len(churn)),
        ].astype(np.float32)
        node_task_count[churn] = rng.randint(0, 5, len(churn))
        vers[churn] += 1
    assert hits_host == hits_dev
    assert hits_dev > 0, "warm O(churn) reuse never engaged on device"
    return hits_dev


_SMALL_MESH_SCRIPT = r"""
import sys
from kube_batch_tpu.utils.backend import force_cpu_devices
assert force_cpu_devices(%(devices)d)
sys.path.insert(0, r"%(testdir)s")
from test_select_device import run_parity_cycles
hits = run_parity_cycles(cycles=4, seed=%(seed)d)
print("SELECT_PARITY_OK", hits)
"""


class TestDeviceSelectionParity:
    def test_parity_churned_cycles_8dev(self):
        # conftest forces 8 CPU devices: cp divides the mesh, so the
        # class-axis NamedSharding of the resident key matrix engages.
        run_parity_cycles(cycles=5, seed=3)

    @pytest.mark.parametrize("devices", [1, 2])
    def test_parity_small_mesh_subprocess(self, devices):
        testdir = os.path.dirname(os.path.abspath(__file__))
        script = _SMALL_MESH_SCRIPT % {
            "devices": devices, "testdir": testdir, "seed": 11 + devices,
        }
        env = dict(os.environ)
        env.update({"PALLAS_AXON_POOL_IPS": "", "JAX_PLATFORMS": "cpu"})
        env.pop("XLA_FLAGS", None)  # subprocess owns its device count
        out = subprocess.run(
            [sys.executable, "-c", script], capture_output=True,
            text=True, timeout=600, env=env, cwd=os.path.dirname(
                os.path.dirname(os.path.dirname(os.path.abspath(__file__)))
            ),
        )
        assert "SELECT_PARITY_OK" in out.stdout, (
            out.stdout, out.stderr[-2000:],
        )


def _one_shot(device_state, monkey_env=None, releasing=False):
    """Single tiny selection pass, returning the CandidateSet."""
    from kube_batch_tpu.solver.masks import CombinedMask
    from kube_batch_tpu.solver.topk import select_candidates

    n, t = 64, 16
    rng = np.random.RandomState(0)
    task_req = np.c_[
        rng.choice([250, 500], t), rng.choice([256, 1024], t)
    ].astype(np.float32)
    node_idle = np.tile(
        np.asarray([32000.0, 131072.0], np.float32), (n, 1)
    )
    releasing_cols = (
        np.full_like(node_idle, 100.0) if releasing
        else np.zeros_like(node_idle)
    )
    mask = CombinedMask(
        node_ok=np.ones(n, bool),
        task_group=np.zeros(t, np.int32),
        group_rows=np.ones((1, n), bool),
        pair_idx=np.zeros((0,), np.int32),
        pair_rows=np.zeros((0, n), bool),
    )
    return select_candidates(
        mask, {}, task_req, task_req, node_idle, node_idle,
        releasing_cols, np.zeros(n, np.int32), np.zeros(n, np.int32),
        np.asarray([10.0, 10.0], np.float32), 1.0, 1.0, 8,
        device_state=device_state,
    )


def _tiny_state():
    from kube_batch_tpu.solver import select_device

    n = 64
    node_idle = np.tile(
        np.asarray([32000.0, 131072.0], np.float32), (n, 1)
    )
    return select_device.standalone_state(
        node_idle, node_idle, np.zeros(n, np.int32),
        np.zeros(n, np.int32), np.ones(n, bool), np.ones((1, n), bool),
    )


class TestDeviceSelectionRouting:
    def test_env_off_switch_labels_host_fallback(self, monkeypatch):
        monkeypatch.setenv("KBT_SELECT_DEVICE", "0")
        cs = _one_shot(_tiny_state())
        assert cs.stats["select_path"] == "host:env-disabled"

    def test_releasing_labels_host_fallback(self):
        cs = _one_shot(_tiny_state(), releasing=True)
        assert cs.stats["select_path"] == "host:releasing"

    def test_device_path_engages_and_counts(self):
        from kube_batch_tpu import metrics

        before = metrics.solver_selection_device.total()
        cs = _one_shot(_tiny_state())
        assert cs.stats["select_path"] == "device"
        assert metrics.solver_selection_device.total() == before + 1

    def test_no_device_state_stays_host(self):
        cs = _one_shot(None)
        assert cs.stats["select_path"] == "host"


class TestLayoutTokenInvalidation:
    """A rack-map move (same device count, same mode) must void BOTH
    cross-cycle selection caches — the carried key rows were laid out
    for the old node->rack decomposition."""

    def _warm_then_flip(self, monkeypatch, device):
        from kube_batch_tpu.solver import sharding, select_device
        from kube_batch_tpu.solver.masks import CombinedMask
        from kube_batch_tpu.solver.topk import select_candidates

        monkeypatch.setitem(sharding._layout_state, "devices", 8)
        monkeypatch.setitem(sharding._layout_state, "rack", None)
        monkeypatch.delenv("KBT_SPARSE_SHARD_MODE", raising=False)

        n, t = 96, 24
        rng = np.random.RandomState(1)
        task_req = np.c_[
            rng.choice([250, 500, 1000], t), rng.choice([256, 1024], t)
        ].astype(np.float32)
        node_idle = np.c_[
            rng.uniform(4000, 32000, n), rng.uniform(8192, 131072, n)
        ].astype(np.float32)
        mask = CombinedMask(
            node_ok=np.ones(n, bool),
            task_group=np.zeros(t, np.int32),
            group_rows=np.ones((1, n), bool),
            pair_idx=np.zeros((0,), np.int32),
            pair_rows=np.zeros((0, n), bool),
        )
        zc = np.zeros(n, np.int32)
        ids = np.arange(n, dtype=np.int64)
        vers = np.zeros(n, np.int64)

        class _Holder:
            pass

        holder = _Holder()

        def run():
            state = None
            if device:
                state = select_device.standalone_state(
                    node_idle, node_idle, zc, zc,
                    np.ones(n, bool), mask.group_rows,
                )
                state.holder = holder
            return select_candidates(
                mask, {}, task_req, task_req, node_idle, node_idle,
                np.zeros_like(node_idle), zc, zc,
                np.asarray([10.0, 10.0], np.float32), 1.0, 1.0, 8,
                cache_holder=holder, node_fp=(ids, vers, None),
                device_state=state,
            )

        run()
        warm = run()
        assert warm.stats["sel_cache_hits"] > 0
        # The rack map moves under the caches (a sharded dispatch on a
        # re-coordinated mesh would pin a different digest).
        monkeypatch.setitem(sharding._layout_state, "rack", "feedbeef")
        cold = run()
        assert cold.stats["sel_cache_hits"] == 0
        return warm, cold

    def test_host_cache_invalidates_on_rack_change(self, monkeypatch):
        warm, cold = self._warm_then_flip(monkeypatch, device=False)
        assert warm.stats["select_path"] == "host"
        assert cold.stats["select_path"] == "host"

    def test_device_engine_invalidates_on_rack_change(self, monkeypatch):
        warm, cold = self._warm_then_flip(monkeypatch, device=True)
        assert warm.stats["select_path"] == "device"
        assert cold.stats["select_path"] == "device"
        assert cold.stats["sel_rows_rebuilt"] > 0
