"""Domain-model helpers.

Mirrors reference pkg/scheduler/api/helpers.go (:26 PodKey, :35 getTaskStatus)
and pkg/apis/utils/utils.go (:26 GetController).
"""

from __future__ import annotations

from .objects import Pod, PodPhase
from .types import TaskStatus


def pod_key(pod: Pod) -> str:
    """Unique key of a pod (reference helpers.go:26-33)."""
    if pod.metadata.uid:
        return pod.metadata.uid
    return f"{pod.namespace}/{pod.name}"


def get_task_status(pod: Pod) -> TaskStatus:
    """Pod phase → TaskStatus (reference helpers.go:35-60)."""
    phase = pod.status.phase
    if phase == PodPhase.RUNNING:
        if pod.metadata.deletion_timestamp is not None:
            return TaskStatus.RELEASING
        return TaskStatus.RUNNING
    if phase == PodPhase.PENDING:
        if pod.metadata.deletion_timestamp is not None:
            return TaskStatus.RELEASING
        if pod.spec.node_name:
            return TaskStatus.BOUND
        return TaskStatus.PENDING
    if phase == PodPhase.UNKNOWN:
        return TaskStatus.UNKNOWN
    if phase == PodPhase.SUCCEEDED:
        return TaskStatus.SUCCEEDED
    if phase == PodPhase.FAILED:
        return TaskStatus.FAILED
    return TaskStatus.UNKNOWN


def get_controller_uid(pod: Pod) -> str:
    """Controller owner UID, used to key shadow PodGroups
    (reference apis/utils/utils.go:26-38)."""
    return pod.metadata.owner_uid or ""
