"""Actions (mirrors reference pkg/scheduler/actions).

Importing this package registers every builtin action with the framework
registry (the reference's factory.go:28-33 / init() pattern). The TPU-native
allocate_tpu action is registered lazily by kube_batch_tpu.ops import."""

from . import allocate, backfill, preempt, reclaim  # noqa: F401
