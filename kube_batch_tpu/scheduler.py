"""Scheduler core loop.

Mirrors reference pkg/scheduler/scheduler.go (:35 struct, :45 NewScheduler,
:63 Run — wait.Until(runOnce, period), :88 runOnce: OpenSession → execute
configured actions in order → CloseSession, with per-action latency metrics)
and pkg/scheduler/util.go (:44 loadSchedulerConf, :32 defaultSchedulerConf).
"""

from __future__ import annotations

import logging
import threading
import time
from typing import List, Optional, Tuple

from . import metrics
from .conf import DEFAULT_SCHEDULER_CONF, Tier, parse_scheduler_conf
from .framework import Action, close_session, get_action, open_session
from .utils import deferred_gc

logger = logging.getLogger(__name__)


def load_scheduler_conf(confstr: str) -> Tuple[List[Action], List[Tier]]:
    """YAML policy → (ordered actions, plugin tiers). Misconfigured action
    names are a hard error (reference scheduler/util.go:44-72)."""
    conf = parse_scheduler_conf(confstr)
    actions: List[Action] = []
    for name in conf.actions.split(","):
        name = name.strip()
        if not name:
            continue
        action, found = get_action(name)
        if not found:
            raise ValueError(f"failed to find Action {name}, ignore it")
        actions.append(action)
    return actions, conf.tiers


class Scheduler:
    def __init__(
        self,
        cache,
        scheduler_conf: Optional[str] = None,
        schedule_period: float = 1.0,
    ):
        """scheduler_conf: YAML policy string or path to one; defaults to the
        reference default policy (allocate, backfill; 2 plugin tiers)."""
        # Ensure builtin registries are populated (blank-import analog,
        # reference cmd/kube-batch/main.go:33-35).
        from . import actions as _actions  # noqa: F401
        from . import plugins as _plugins  # noqa: F401

        self.cache = cache
        self.schedule_period = schedule_period
        confstr = scheduler_conf or DEFAULT_SCHEDULER_CONF
        if "\n" not in confstr and confstr.endswith((".yaml", ".yml")):
            with open(confstr) as f:
                confstr = f.read()
        self.actions, self.tiers = load_scheduler_conf(confstr)

    def run(self, stop_event: Optional[threading.Event] = None) -> None:
        """reference scheduler.go:63-85"""
        stop = stop_event or threading.Event()
        self.cache.run(stop)
        self.cache.wait_for_cache_sync(stop)
        while not stop.is_set():
            start = time.perf_counter()
            try:
                self.run_once()
            except Exception:
                logger.exception("scheduling cycle failed")
            elapsed = time.perf_counter() - start
            remaining = max(0.0, self.schedule_period - elapsed)
            if remaining > 0:
                # Think-time drain: absorb this cycle's async bind/evict
                # backlog while the loop would otherwise sleep, so the
                # next cycle's overlapped solve window starts from an
                # empty side-effect queue (allocate_tpu parks on the
                # same queue inside the solve's shadow). Sliced waits so
                # the stop event stays responsive mid-drain.
                deadline = time.perf_counter() + remaining
                try:
                    while not stop.is_set():
                        left = deadline - time.perf_counter()
                        if left <= 0:
                            break
                        if self.cache.wait_for_side_effects(
                            timeout=min(0.2, left)
                        ):
                            break
                except Exception:
                    logger.exception("think-time side-effect drain failed")
                remaining = max(0.0, deadline - time.perf_counter())
            stop.wait(remaining)

    def run_once(self) -> None:
        """One scheduling cycle (reference scheduler.go:88-103). GC is
        deferred for the cycle's duration — collections triggered by the
        apply phase's allocation burst otherwise stop the world mid-cycle
        (~350 ms at 50k tasks); the deferred collection runs in the
        scheduler's think-time gap instead (utils/gc_guard.py)."""
        cycle_start = time.perf_counter()
        with deferred_gc():
            ssn = open_session(self.cache, self.tiers)
            try:
                for action in self.actions:
                    action_start = time.perf_counter()
                    action.initialize()
                    action.execute(ssn)
                    action.un_initialize()
                    metrics.update_action_duration(
                        action.name(), time.perf_counter() - action_start
                    )
            finally:
                close_session(ssn)
        metrics.update_e2e_duration(time.perf_counter() - cycle_start)
