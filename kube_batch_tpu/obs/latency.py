"""Placement-latency SLI ledger + replay-stable decision audit log.

Until now the only arrival-to-placement signal in the system was the
bench's single micro-cycle number; nothing answered "how long does a
pod wait, in which stage, per queue" — the question every subsequent
ROADMAP item (micro-primary flip, SLO serving classes, closed-loop
autotuning) needs answered continuously. Two instruments live here:

**PlacementLedger** — every pending pod of this scheduler is stamped at
arrival (``cache/event_handlers.add_pod``) and tracked through stage
transitions until its bind APPLIES (the journal-mark seam in
``cache._bind_side_effect`` — the applied timestamp is the truthful
one, not the dispatch):

- ``queue_wait``  arrival → the solving cycle that placed it (minus
  that cycle's solve time); cycles considered-but-unplaced are counted
  per job, tagged with the explain verdict reason;
- ``solve``       the placing cycle's tensorize+solve+apply time
  (attributed to every pod it placed), labeled with the cycle kind
  (periodic vs micro), warm outcome and winning solver rung;
- ``dispatch``    placed → bind batch staged on the side-effect pool;
- ``bind``        dispatch → bind applied (or failed);
- ``total``       arrival → applied.

A bind failure or a preempt/evict RESTARTS the clock (``requeued``
stage, requeue counter); ledger entries are GC'd with their pod/job
(the PR 6 metrics-GC pattern — no per-pod leak). Gang semantics: a
gang's latency is its LAST member's bind-applied; per-member and
per-gang (``gang_total``) series are both kept.

Aggregation: per-(queue, cycle-kind, stage) DDSketch percentiles
(reusing the PR 6 ``QuantileSketch``), the Prometheus histogram
``pod_placement_latency_seconds{stage,queue,cycle_kind}`` on
MS_BUCKETS, the ``/debug/latency`` + ``/debug/vars`` snapshots, the
flight-dump embed, and per-cycle ``placement_p99:<queue>`` /
``latency_entries`` telemetry series (the soak drift/leak detectors
fit those).

**AuditLog** — a bounded append-only ring (``KBT_AUDIT_CAPACITY``) of
one structured record per job per cycle it was touched: verdict or
placement, counts, victim-selection outcome, solver attribution, and
latency-so-far. Records are stamped with the LEDGER CLOCK — the
scheduler's injectable clock, so the simulator's audit stream is
virtual-clock-stamped and **byte-identical under replay**
(``make latency-smoke`` pins this; wall-clock never enters a record,
honoring the kbtlint replay-determinism contract). ``dump_jsonl``
writes one canonical-JSON record per line, flight-recorder style.

The enabled path is deliberately cheap (one small dict op per stage
transition under one lock); the bench ``obs`` section pins ledger +
audit cost against the same <1%-of-an-idle-cycle budget as the tracer.
``KBT_LATENCY=0`` disables both at the source.
"""

from __future__ import annotations

import json
import logging
import os
import time
from collections import deque
from typing import Callable, Dict, Iterable, List, Optional, Tuple

from ..utils.lockdebug import witness_writes, wrap_lock

logger = logging.getLogger(__name__)

LATENCY_ENV = "KBT_LATENCY"                 # "0" disables ledger + audit
AUDIT_CAPACITY_ENV = "KBT_AUDIT_CAPACITY"   # audit ring size (records)
# Serving SLO-attainment target (fraction of serving placements that
# must meet their per-job latency target). Defines the violation
# budget: misses allowed = (1 - target) x targeted placements.
SERVING_TARGET_ENV = "KBT_SERVING_ATTAINMENT_TARGET"
DEFAULT_SERVING_TARGET = 0.99
DEFAULT_AUDIT_CAPACITY = 4096
# Completed-entry ring served by /debug/latency (forensics only — the
# percentile sketches are the durable aggregate).
DONE_CAPACITY = 256

# Stage taxonomy (doc/design/observability.md carries the full table).
STAGES = ("queue_wait", "solve", "dispatch", "bind", "total")
GANG_STAGE = "gang_total"

QUANTILES = (("p50", 0.5), ("p95", 0.95), ("p99", 0.99))


def latency_enabled_from_env() -> bool:
    return os.environ.get(LATENCY_ENV, "1") != "0"


def serving_target_from_env() -> float:
    try:
        t = float(os.environ.get(
            SERVING_TARGET_ENV, DEFAULT_SERVING_TARGET
        ))
    except ValueError:
        return DEFAULT_SERVING_TARGET
    return min(1.0, max(0.0, t))


class _PodEntry:
    """One pending pod's stage stamps (ledger-clock values)."""

    __slots__ = (
        "uid", "pod", "job", "queue", "arrival_ts", "placed_ts",
        "dispatch_ts", "stage", "cycle_kind", "solve_s", "requeues",
        "last_reason",
    )

    def __init__(self, uid: str, pod: str, job: str, now: float):
        self.uid = uid
        self.pod = pod
        self.job = job
        self.queue = ""
        self.arrival_ts = now
        self.placed_ts: Optional[float] = None
        self.dispatch_ts: Optional[float] = None
        self.stage = "pending"
        self.cycle_kind = "periodic"
        self.solve_s = 0.0
        self.requeues = 0
        self.last_reason: Optional[str] = None

    def restart(self, now: float, reason: str) -> None:
        """A retry/evict restarts the clock: the next placement's
        latency is measured from the requeue, not the first arrival."""
        self.arrival_ts = now
        self.placed_ts = None
        self.dispatch_ts = None
        self.solve_s = 0.0
        self.stage = "requeued"
        self.requeues += 1
        self.last_reason = reason

    def to_dict(self) -> dict:
        return {
            "uid": self.uid,
            "pod": self.pod,
            "job": self.job,
            "queue": self.queue,
            "stage": self.stage,
            "cycle_kind": self.cycle_kind,
            "arrival_ts": round(self.arrival_ts, 6),
            "requeues": self.requeues,
            "last_reason": self.last_reason,
        }


class _JobWait:
    """Per-job queue-wait bookkeeping: cycles considered-but-unplaced
    (tagged with the explain verdict reason) and gang accounting."""

    __slots__ = (
        "cycles_waited", "waiting_since", "last_reason", "queue",
        "first_arrival_ts", "arrivals", "applied",
    )

    def __init__(self, now: float):
        self.cycles_waited = 0
        self.waiting_since = now
        self.last_reason: Optional[str] = None
        self.queue = ""
        self.first_arrival_ts: Optional[float] = now
        self.arrivals = 0
        self.applied = 0


class _StageStats:
    __slots__ = ("count", "sum", "sketch")

    def __init__(self):
        from .telemetry import QuantileSketch

        self.count = 0
        self.sum = 0.0
        self.sketch = QuantileSketch()

    def add(self, v: float) -> None:
        self.count += 1
        self.sum += v
        self.sketch.add(v)

    def to_dict(self) -> dict:
        out = {
            "count": self.count,
            "mean_s": round(self.sum / self.count, 6) if self.count else 0.0,
        }
        for name, q in QUANTILES:
            out[f"{name}_s"] = round(self.sketch.quantile(q), 6)
        return out


class PlacementLedger:
    """Per-pod arrival→bind latency ledger (module docstring)."""

    def __init__(self):
        self._lock = wrap_lock("obs.latency")
        # Written ONLY here (construction) — hot-path reads stay
        # lock-free; tests flip it through configure().
        self.enabled = latency_enabled_from_env()
        self._clock = time.monotonic
        self.reset()
        # KBT_LOCK_DEBUG=2 write-witness (no-op otherwise).
        witness_writes(self, "obs.latency", (
            "_entries", "_by_job", "_jobs", "_sketches", "_done",
            "stamped", "applied", "bind_failures", "requeues",
            "gang_samples", "_cycle", "_cycle_kind",
            "_serving_jobs", "_slo_targets", "_serving_pending",
            "_job_slo_applied", "_job_slo_missed", "_slo_counts",
            "_serving_arrival", "_serving_target",
        ))

    # -- lifecycle -----------------------------------------------------------

    def reset(self) -> None:
        """Drop all entries/sketches/counters (sim run boundaries,
        tests). The injected clock survives a reset."""
        with self._lock:
            self._entries: Dict[str, _PodEntry] = {}
            # job -> set of pending member uids (order never read —
            # gang closure only needs emptiness; a list would cost
            # O(members) per applied, O(n^2) per large gang).
            self._by_job: Dict[str, set] = {}
            self._jobs: Dict[str, _JobWait] = {}
            self._sketches: Dict[Tuple[str, str, str], _StageStats] = {}
            self._done: deque = deque(maxlen=DONE_CAPACITY)
            self.stamped = 0
            self.applied = 0
            self.bind_failures = 0
            self.requeues = 0
            self.gang_samples = 0
            self._cycle = 0
            self._cycle_kind = "periodic"
            # -- serving SLO accounting (doc/design/serving.md) --------
            # Jobs classified serving at arrival; jobs with a latency
            # target additionally keyed into _slo_targets.
            self._serving_jobs: set = set()
            self._slo_targets: Dict[str, float] = {}
            # uid -> SLO deadline (arrival/restart ts + target) for the
            # pending serving entries — the serving-pressure signal.
            self._serving_pending: Dict[str, float] = {}
            # Per-job targeted placements and misses (the preempt
            # gate's violation-budget input; GC'd with the job).
            self._job_slo_applied: Dict[str, int] = {}
            self._job_slo_missed: Dict[str, int] = {}
            # Per-class [targeted placements, met, missed].
            self._slo_counts: Dict[str, List[int]] = {}
            # Set on a serving arrival, consumed by the scheduler's
            # micro coalescing window (serving arrivals ride the
            # minimum window — highest coalescing priority).
            self._serving_arrival = False
            self._serving_target = serving_target_from_env()

    def configure(
        self,
        enabled: Optional[bool] = None,
        clock: Optional[Callable[[], float]] = None,
    ) -> None:
        """Install an injectable clock (the scheduler's — virtual in
        the simulator, so every stamp is replay-deterministic) and/or
        flip the enabled gate. ``clock=None`` leaves it unchanged."""
        with self._lock:
            if clock is not None:
                self._clock = clock
            if enabled is not None:
                object.__setattr__(self, "enabled", bool(enabled))

    def now(self) -> float:
        with self._lock:
            return self._clock()

    # -- cycle context -------------------------------------------------------

    def begin_cycle(self, cycle: int, kind: str = "periodic") -> None:
        """Stamp the current scheduling-cycle context (Scheduler
        run_once/run_micro). Cycle numbers come from the scheduler's
        deterministic counter, so audit records replay bit-equal."""
        if not self.enabled:
            return
        with self._lock:
            self._cycle = int(cycle)
            self._cycle_kind = kind

    def cycle_info(self) -> Tuple[int, str, float]:
        """(cycle, kind, ledger-clock now) for audit stamping."""
        with self._lock:
            return self._cycle, self._cycle_kind, self._clock()

    # -- stage transitions ---------------------------------------------------

    def note_arrival(
        self,
        uid: str,
        pod_key: str,
        job: str,
        workload_class: str = "batch",
        slo_target: Optional[float] = None,
    ) -> None:
        """A pending pod of ours landed in the mirror (the cache event
        handler's add_pod seam). Idempotent per uid. Serving pods carry
        their class + placement-latency target so the ledger can keep
        per-class SLO accounting and the serving-pressure signal."""
        if not self.enabled:
            return
        with self._lock:
            if uid in self._entries:
                return
            now = self._clock()
            self._entries[uid] = _PodEntry(uid, pod_key, job, now)
            self._track_locked(uid, job, now)
            self.stamped += 1
            if workload_class == "serving":
                self._serving_jobs.add(job)
                self._serving_arrival = True
                if slo_target is not None and slo_target > 0:
                    self._slo_targets[job] = float(slo_target)
                    self._serving_pending[uid] = now + float(slo_target)

    def _track_locked(self, uid: str, job: str, now: float) -> None:
        """Register one entry in the job index + wait record (caller
        holds the lock and has already created the entry)."""
        self._by_job.setdefault(job, set()).add(uid)
        jw = self._jobs.get(job)
        if jw is None:
            jw = self._jobs[job] = _JobWait(now)
        if jw.first_arrival_ts is None:
            # A new gang wave after the previous one fully applied.
            jw.first_arrival_ts = now
            jw.waiting_since = now
        jw.arrivals += 1

    def note_unplaced_job(
        self, job: str, reason: str, queue: str = "",
    ) -> Optional[Tuple[int, float, float]]:
        """One solving cycle considered this job and left it (partly)
        unplaced, classified as ``reason`` by obs/explain. Returns
        ``(cycles_waited, waiting_since, waiting_seconds)`` for the
        verdict detail, or None when disabled/unknown."""
        if not self.enabled:
            return None
        with self._lock:
            jw = self._jobs.get(job)
            if jw is None:
                jw = self._jobs[job] = _JobWait(self._clock())
            jw.cycles_waited += 1
            jw.last_reason = reason
            if queue:
                jw.queue = queue
            now = self._clock()
            return (
                jw.cycles_waited,
                round(jw.waiting_since, 6),
                round(max(0.0, now - jw.waiting_since), 6),
            )

    def job_wait_info(self, job: str) -> Optional[Tuple[int, float, float]]:
        """(cycles_waited, waiting_since, waiting_seconds) or None."""
        with self._lock:
            jw = self._jobs.get(job)
            if jw is None:
                return None
            now = self._clock()
            return (
                jw.cycles_waited,
                round(jw.waiting_since, 6),
                round(max(0.0, now - jw.waiting_since), 6),
            )

    def note_placed(
        self,
        uid_jobs: Iterable[Tuple[str, str]],
        job_queues: Dict[str, str],
        kind: str = "periodic",
        solve_s: float = 0.0,
    ) -> None:
        """The solve placed these tasks this cycle (allocate_tpu apply;
        ``uid_jobs`` is an iterable of ``(uid, job)``). Entries unknown
        to the ledger (tasks predating the process, bench sessions that
        bypass add_pod) are created here so dispatch/bind stages still
        measure."""
        if not self.enabled:
            return
        with self._lock:
            now = self._clock()
            for uid, job in uid_jobs:
                e = self._entries.get(uid)
                if e is None:
                    e = self._entries[uid] = _PodEntry(uid, uid, job, now)
                    self._track_locked(uid, job, now)
                    self.stamped += 1
                e.placed_ts = now
                e.stage = "placed"
                e.cycle_kind = kind
                e.solve_s = solve_s
                queue = job_queues.get(job)
                if queue:
                    e.queue = queue
                    jw = self._jobs.get(job)
                    if jw is not None:
                        jw.queue = queue

    def note_dispatched(self, uids: Iterable[str]) -> None:
        """Bind batch staged on the side-effect pool for these tasks."""
        if not self.enabled:
            return
        with self._lock:
            now = self._clock()
            for uid in uids:
                e = self._entries.get(uid)
                if e is not None:
                    e.dispatch_ts = now
                    e.stage = "dispatched"

    def note_applied(self, uid: str) -> None:
        """The bind side effect APPLIED (the journal-mark seam): the
        truthful end of this pod's placement latency. Emits the stage
        samples, advances the gang accounting, and drops the entry."""
        if not self.enabled:
            return
        metric_samples: List[Tuple[str, str, str, float]] = []
        slo_sample: Optional[Tuple[str, bool]] = None
        with self._lock:
            e = self._entries.pop(uid, None)
            if e is None:
                return
            self._serving_pending.pop(uid, None)
            now = self._clock()
            placed = e.placed_ts if e.placed_ts is not None else (
                e.dispatch_ts if e.dispatch_ts is not None else now
            )
            dispatch = e.dispatch_ts if e.dispatch_ts is not None else placed
            solve = max(0.0, min(e.solve_s, placed - e.arrival_ts))
            stages = {
                "queue_wait": max(0.0, placed - e.arrival_ts - solve),
                "solve": solve,
                "dispatch": max(0.0, dispatch - placed),
                "bind": max(0.0, now - dispatch),
                "total": max(0.0, now - e.arrival_ts),
            }
            queue, kind = e.queue or "-", e.cycle_kind
            for stage, v in stages.items():
                self._stage_stats(queue, kind, stage).add(v)
                metric_samples.append((stage, queue, kind, v))
            self.applied += 1
            # SLO verdict at the truthful bind-applied moment: a pod of
            # a targeted job met its SLO iff total <= target.
            target = self._slo_targets.get(e.job)
            if target is not None:
                cls = (
                    "serving" if e.job in self._serving_jobs else "batch"
                )
                met = stages["total"] <= target
                counts = self._slo_counts.get(cls)
                if counts is None:
                    counts = self._slo_counts[cls] = [0, 0, 0]
                counts[0] += 1
                counts[1 if met else 2] += 1
                self._job_slo_applied[e.job] = (
                    self._job_slo_applied.get(e.job, 0) + 1
                )
                if not met:
                    self._job_slo_missed[e.job] = (
                        self._job_slo_missed.get(e.job, 0) + 1
                    )
                slo_sample = (cls, met)
            members = self._by_job.get(e.job)
            if members is not None and uid in members:
                members.remove(uid)
            jw = self._jobs.get(e.job)
            if jw is not None:
                jw.applied += 1
                if queue != "-":
                    jw.queue = queue
                # Gang semantics: the gang's latency is its LAST
                # member's bind-applied. When no member of the current
                # wave is left pending, close the wave; later arrivals
                # (rebirths, scale-ups) open a new one.
                if not members and jw.first_arrival_ts is not None:
                    gang_total = max(0.0, now - jw.first_arrival_ts)
                    if jw.applied > 1:
                        self._stage_stats(
                            jw.queue or queue, kind, GANG_STAGE
                        ).add(gang_total)
                        self.gang_samples += 1
                        metric_samples.append((
                            GANG_STAGE, jw.queue or queue, kind,
                            gang_total,
                        ))
                    jw.first_arrival_ts = None
                    jw.arrivals = 0
                    jw.applied = 0
                    jw.cycles_waited = 0
            self._done.append({
                "pod": e.pod, "job": e.job, "queue": queue,
                "cycle_kind": kind, "requeues": e.requeues,
                **{f"{k}_s": round(v, 6) for k, v in stages.items()},
            })
        # Prometheus outside the ledger lock (the registry has its own
        # locks; no cross-lock hold).
        try:
            from .. import metrics

            for stage, q, kind, v in metric_samples:
                metrics.observe_placement_latency(stage, q, kind, v)
            if slo_sample is not None:
                cls, met = slo_sample
                metrics.pod_slo_placements.inc(
                    (cls, "met" if met else "missed")
                )
                serving = self.serving_summary()
                metrics.serving_slo_attainment.set(
                    serving["attainment_pct"] / 100.0
                )
                metrics.serving_slo_budget_burn.set(
                    serving["budget_burn"]
                )
        except Exception:  # pragma: no cover - metrics must never kill
            logger.exception("placement latency metric update failed")

    def note_bind_failed(self, uid: str, reason: str = "bind-failed") -> None:
        """The bind side effect failed/reverted: the task goes back to
        scheduling, and its clock restarts (``requeued``)."""
        if not self.enabled:
            return
        with self._lock:
            e = self._entries.get(uid)
            if e is None:
                return
            e.restart(self._clock(), reason)
            self.bind_failures += 1
            self.requeues += 1
            self._restart_serving_deadline(e)
            jw = self._jobs.get(e.job)
            if jw is not None:
                jw.waiting_since = e.arrival_ts

    def note_requeued(self, uid: str, reason: str, job: str = "") -> None:
        """Preempt/evict restarts the pod's clock. An already-applied
        pod's entry was dropped at bind-applied — re-create it under
        its JOB (callers pass it) so the re-placement's gang accounting
        and per-queue series stay attributed; a job-less orphan entry
        would silently fall out of both."""
        if not self.enabled:
            return
        with self._lock:
            now = self._clock()
            e = self._entries.get(uid)
            if e is None:
                e = self._entries[uid] = _PodEntry(uid, uid, job, now)
                self._track_locked(uid, e.job, now)
                self.stamped += 1
            e.restart(now, reason)
            self.requeues += 1
            self._restart_serving_deadline(e)

    def _restart_serving_deadline(self, e: _PodEntry) -> None:
        """A restarted clock restarts the pod's SLO deadline too
        (caller holds the lock)."""
        target = self._slo_targets.get(e.job)
        if target is not None:
            self._serving_pending[e.uid] = e.arrival_ts + target

    # -- GC (the PR 6 metrics-GC pattern: no per-pod leak) -------------------

    def forget_pod(self, uid: str) -> None:
        with self._lock:
            self._serving_pending.pop(uid, None)
            e = self._entries.pop(uid, None)
            if e is None:
                return
            members = self._by_job.get(e.job)
            if members is not None:
                if uid in members:
                    members.remove(uid)
                if not members:
                    # Last tracked member gone: the wait record goes
                    # too (covers jobs whose cleanup hook never fires —
                    # e.g. shadow-group pods filed under the pod uid).
                    self._by_job.pop(e.job, None)
                    self._jobs.pop(e.job, None)
                    self._forget_job_serving_locked(e.job)

    def forget_job(self, job: str) -> None:
        """A job left the mirror (terminated-job cleanup): drop its
        wait record and every member entry with it."""
        with self._lock:
            for uid in self._by_job.pop(job, ()):
                self._entries.pop(uid, None)
                self._serving_pending.pop(uid, None)
            self._jobs.pop(job, None)
            self._forget_job_serving_locked(job)

    def _forget_job_serving_locked(self, job: str) -> None:
        """Per-job serving state dies with the job (metrics-GC
        pattern); the cumulative class counters are run-level and
        stay."""
        self._serving_jobs.discard(job)
        self._slo_targets.pop(job, None)
        self._job_slo_applied.pop(job, None)
        self._job_slo_missed.pop(job, None)

    # -- serving SLO surface (doc/design/serving.md) -------------------------

    def serving_arrival_pending(self, consume: bool = True) -> bool:
        """True when a serving pod arrived since the last check. The
        scheduler's micro coalescing window consumes this to give
        serving arrivals the minimum (highest-priority) window."""
        if not self.enabled:
            return False
        with self._lock:
            pending = self._serving_arrival
            if consume:
                self._serving_arrival = False
            return pending

    def serving_pressure(self) -> bool:
        """True when some pending serving pod has outlived its
        placement-latency target — the early-fairness-pass trigger
        (scheduler satellite: preempt/reclaim must not starve behind a
        micro-cycle storm while a serving SLO is burning)."""
        if not self.enabled:
            return False
        with self._lock:
            if not self._serving_pending:
                return False
            now = self._clock()
            return any(
                deadline <= now
                for deadline in self._serving_pending.values()
            )

    def serving_budget_ok(self, job: str) -> bool:
        """Whether ``job`` could absorb ONE more SLO miss and stay
        inside its violation budget (misses allowed = (1 - target) x
        targeted placements). The preempt/reclaim gate excludes serving
        victims for which this is False — evicting one forces a
        re-placement that is overwhelmingly likely to miss. Jobs
        without a latency target always pass (the replica floor is
        their only protection). Eviction-monotone and claimant-
        independent by construction: the verdict reads only the
        victim job's own cumulative counters, which evictions never
        improve."""
        if not self.enabled:
            return True
        with self._lock:
            if job not in self._slo_targets:
                return True
            applied = self._job_slo_applied.get(job, 0)
            missed = self._job_slo_missed.get(job, 0)
            allowed = (1.0 - self._serving_target) * applied
            return missed + 1 <= allowed

    def serving_summary(self) -> dict:
        """Per-class SLO accounting (/debug/vars ``serving`` key, sim
        report, bench): targeted placements, met/missed, attainment %,
        violation-budget burn (missed / allowed; >1 = budget blown)."""
        with self._lock:
            classes = {
                cls: {
                    "placed": counts[0],
                    "met": counts[1],
                    "missed": counts[2],
                    "attainment_pct": round(
                        100.0 * counts[1] / counts[0], 3
                    ) if counts[0] else 100.0,
                }
                for cls, counts in sorted(self._slo_counts.items())
            }
            serving = self._slo_counts.get("serving", [0, 0, 0])
            allowed = (1.0 - self._serving_target) * serving[0]
            return {
                "target": self._serving_target,
                "serving_jobs": len(self._serving_jobs),
                "pending_targeted": len(self._serving_pending),
                "classes": classes,
                "attainment_pct": (
                    round(100.0 * serving[1] / serving[0], 3)
                    if serving[0] else 100.0
                ),
                "violations": serving[2],
                "budget_burn": (
                    round(serving[2] / allowed, 3) if allowed > 0
                    else (float(serving[2]))
                ),
            }

    # -- aggregation ---------------------------------------------------------

    def _stage_stats(self, queue: str, kind: str, stage: str) -> _StageStats:
        key = (queue, kind, stage)
        stats = self._sketches.get(key)
        if stats is None:
            stats = self._sketches[key] = _StageStats()
        return stats

    def entry_count(self) -> int:
        with self._lock:
            return len(self._entries)

    def queue_p99_seconds(self) -> Dict[str, float]:
        """Per-queue p99 of the ``total`` stage (kinds merged by max —
        the SLI is the worst path), for the telemetry
        ``placement_p99:<queue>`` series the soak drift detector
        bounds."""
        with self._lock:
            out: Dict[str, float] = {}
            for (queue, _kind, stage), stats in self._sketches.items():
                if stage != "total" or queue == "-":
                    continue
                p99 = stats.sketch.quantile(0.99)
                if p99 > out.get(queue, 0.0):
                    out[queue] = p99
            return out

    def telemetry_sample(self) -> Dict[str, float]:
        """Per-cycle keys folded into the telemetry time-series:
        ledger occupancy (leak watermark) + per-queue p99."""
        values = {"latency_entries": float(self.entry_count())}
        for queue, p99 in self.queue_p99_seconds().items():
            values[f"placement_p99:{queue}"] = round(p99, 6)
        # Serving SLO-miss rate (cumulative; emitted only once serving
        # placements exist so batch-only telemetry stays unchanged) —
        # the soak drift detector bounds this series.
        with self._lock:
            serving = self._slo_counts.get("serving")
        if serving and serving[0]:
            values["serving_slo_miss_rate"] = round(
                serving[2] / serving[0], 6
            )
        return values

    def percentiles(self) -> dict:
        """Nested {queue: {cycle_kind: {stage: {count, mean, p50/p95/
        p99}}}} over everything applied so far."""
        with self._lock:
            out: dict = {}
            for (queue, kind, stage), stats in sorted(
                self._sketches.items()
            ):
                out.setdefault(queue, {}).setdefault(kind, {})[stage] = (
                    stats.to_dict()
                )
            return out

    def stage_percentiles(self) -> dict:
        """Queue/kind-merged per-stage percentiles (the bench
        ``arrival_latency`` headline rows). Merging re-folds the
        per-key sketches into one per stage — exactly mergeable by
        construction (log-bucket counts add)."""
        with self._lock:
            merged: Dict[str, _StageStats] = {}
            for (_q, _k, stage), stats in self._sketches.items():
                agg = merged.get(stage)
                if agg is None:
                    agg = merged[stage] = _StageStats()
                agg.count += stats.count
                agg.sum += stats.sum
                agg.sketch.merge(stats.sketch)
            return {
                stage: stats.to_dict()
                for stage, stats in sorted(merged.items())
            }

    def summary(self) -> dict:
        """Small engagement summary (/debug/vars, sim report, flight
        embed): counters + per-queue p99 + merged stage p99s."""
        with self._lock:
            counters = {
                "enabled": self.enabled,
                "stamped": self.stamped,
                "applied": self.applied,
                "pending_entries": len(self._entries),
                "bind_failures": self.bind_failures,
                "requeues": self.requeues,
                "gang_samples": self.gang_samples,
            }
        counters["queue_p99_s"] = {
            q: round(v, 6) for q, v in self.queue_p99_seconds().items()
        }
        counters["stage_p99_s"] = {
            stage: stats["p99_s"]
            for stage, stats in self.stage_percentiles().items()
        }
        counters["serving"] = self.serving_summary()
        return counters

    def snapshot(self) -> dict:
        """The ``/debug/latency`` payload: summary + full percentile
        tree + the recent completed-entry ring + live entry sample."""
        with self._lock:
            done = list(self._done)
            live = [
                e.to_dict() for _uid, e in sorted(self._entries.items())
            ][:64]
        return {
            "type": "placement-latency",
            **self.summary(),
            "percentiles": self.percentiles(),
            "recent_applied": done,
            "pending_sample": live,
        }


# -- decision audit log -------------------------------------------------------


class AuditLog:
    """Bounded append-only ring of per-(job, cycle) decision records
    (module docstring). Records carry ONLY deterministic fields —
    scheduler cycle counter, ledger-clock stamps (virtual in the sim),
    verdicts, counts — so a replayed sim emits a byte-identical stream.
    Wall-clock appears nowhere in a record; dump metadata that needs it
    stays out of the JSONL body."""

    def __init__(self, capacity: Optional[int] = None):
        if capacity is None:
            try:
                capacity = int(os.environ.get(
                    AUDIT_CAPACITY_ENV, DEFAULT_AUDIT_CAPACITY
                ))
            except ValueError:
                capacity = DEFAULT_AUDIT_CAPACITY
        self._lock = wrap_lock("obs.audit")
        self.capacity = max(16, capacity)
        self._reset_unlocked()
        witness_writes(self, "obs.audit", ("_seq", "dropped"))

    def _reset_unlocked(self) -> None:
        self._ring: deque = deque(maxlen=self.capacity)
        self._seq = 0
        self.dropped = 0

    def reset(self) -> None:
        with self._lock:
            self._reset_unlocked()

    def configure(self, capacity: int) -> None:
        with self._lock:
            self.capacity = max(16, int(capacity))
            self._reset_unlocked()

    def append(self, record: dict) -> None:
        """Append one decision record; stamps the monotone seq and the
        ledger cycle context (cycle, kind, vclock)."""
        if not LEDGER.enabled:
            return
        cycle, kind, now = LEDGER.cycle_info()
        with self._lock:
            self._seq += 1
            if len(self._ring) == self._ring.maxlen:
                self.dropped += 1
            self._ring.append({
                "seq": self._seq,
                "cycle": cycle,
                "kind": record.get("kind", kind),
                "vclock": round(now, 6),
                **{k: v for k, v in record.items() if k != "kind"},
            })

    def records(self) -> List[dict]:
        with self._lock:
            return [dict(r) for r in self._ring]

    def meta(self) -> dict:
        with self._lock:
            return {
                "capacity": self.capacity,
                "records": len(self._ring),
                "seq": self._seq,
                "dropped": self.dropped,
            }

    def dump_lines(self) -> List[str]:
        """Canonical JSONL body (sorted keys, one record per line) —
        the byte-compared replay artifact."""
        return [
            json.dumps(r, sort_keys=True) for r in self.records()
        ]

    def dump_jsonl(self, path: str) -> str:
        """Write the stream to ``path`` (write-then-rename, like the
        flight recorder's dumps)."""
        parent = os.path.dirname(os.path.abspath(path))
        os.makedirs(parent, exist_ok=True)
        tmp = f"{path}.{os.getpid()}.tmp"
        with open(tmp, "w") as f:
            for line in self.dump_lines():
                f.write(line + "\n")
        os.replace(tmp, path)
        return path


LEDGER = PlacementLedger()
AUDIT = AuditLog()
