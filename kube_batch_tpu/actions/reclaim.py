"""Reclaim action: cross-queue eviction for starving queues.

Mirrors reference actions/reclaim/reclaim.go:41-196: for each non-overused
queue, pop starving job/task by order fns; per node, collect RUNNING tasks of
OTHER queues → ssn.reclaimable victims → ssn.evict("reclaim") until the
request is covered → ssn.pipeline the claimant. Direct evictions, no
Statement (no rollback).
"""

from __future__ import annotations

import logging

from ..api import Resource, TaskStatus
from ..framework import Action, register_action
from ..obs import explain
from ..utils import PriorityQueue
from ..utils.scheduler_helper import FeasibilityMemo

logger = logging.getLogger(__name__)

# Reclaimable fns audited against the exhausted-node memo's soundness
# contract (claimant-independent + eviction-monotone — see
# Session.add_reclaimable_fn). A reclaimable plugin OUTSIDE this set
# disables the memo for the cycle: an upstream-style
# priority-vs-claimant verdict could flip a node from victimless to
# victim-bearing for a later claimant, which the memo would hide.
MEMO_SAFE_RECLAIMABLE = frozenset({"proportion", "gang", "conformance", "serving"})


class ReclaimAction(Action):
    def name(self) -> str:
        return "reclaim"

    @staticmethod
    def _sim_gang_fits(memo, claimant, peeked):
        """First-fit-decreasing placement sim for the skip-eviction guard.
        Only sound for gangs WITHOUT member-vs-member constraints (caller
        gates on that): each member's predicate verdict is then a pure
        function of its spec's constraint fields against current node
        state, so members with equal constraint specs share one feasible
        set via the cycle-scoped memo (homogeneous gangs — the common
        case — cost one predicate pass total, shared with the outer
        claimant scan)."""
        members = sorted(
            [claimant] + peeked,
            key=lambda t: (t.init_resreq.milli_cpu, t.init_resreq.memory),
            reverse=True,
        )
        # One memo lookup per DISTINCT member spec (gangs are usually
        # uniform — the profile showed per-member re-lookups rebuilding
        # the filtered node list 10x per sim), and node state cloned
        # LAZILY on first mutation: a failing sim walks every node and
        # must not clone the whole cluster's vectors on the way.
        feas_cache: list = []  # [(spec, nodes)]
        sim = {}  # node name -> [idle, releasing] mutable copies
        for member in members:
            spec = member.pod.spec
            nodes = None
            for seen_spec, cached in feas_cache:
                if spec is seen_spec or (
                    spec.node_selector == seen_spec.node_selector
                    and spec.affinity == seen_spec.affinity
                    and spec.tolerations == seen_spec.tolerations
                ):
                    nodes = cached
                    break
            if nodes is None:
                nodes = memo.feasible(member)
                feas_cache.append((spec, nodes))
            req = member.init_resreq
            for node in nodes:
                entry = sim.get(node.name)
                idle = entry[0] if entry is not None else node.idle
                releasing = (
                    entry[1] if entry is not None else node.releasing
                )
                if req.less_equal(idle):
                    if entry is None:
                        entry = sim[node.name] = [
                            node.idle.clone(), node.releasing.clone(),
                        ]
                    entry[0].sub(req)
                    break
                if req.less_equal(releasing):
                    if entry is None:
                        entry = sim[node.name] = [
                            node.idle.clone(), node.releasing.clone(),
                        ]
                    entry[1].sub(req)
                    break
            else:
                return False
        return True

    def execute(self, ssn) -> None:
        queues = PriorityQueue(ssn.queue_order_fn)
        queue_map = {}
        preemptors_map = {}
        preemptor_tasks = {}

        for job in ssn.jobs.values():
            queue = ssn.queues.get(job.queue)
            if queue is None:
                logger.error(
                    "Failed to find Queue <%s> for Job <%s/%s>",
                    job.queue, job.namespace, job.name,
                )
                continue
            if queue.uid not in queue_map:
                queue_map[queue.uid] = queue
                queues.push(queue)
            if job.task_status_index.get(TaskStatus.PENDING):
                if job.queue not in preemptors_map:
                    preemptors_map[job.queue] = PriorityQueue(ssn.job_order_fn)
                preemptors_map[job.queue].push(job)
                preemptor_tasks[job.uid] = PriorityQueue(ssn.task_order_fn)
                for task in job.task_status_index[TaskStatus.PENDING].values():
                    preemptor_tasks[job.uid].push(task)

        # Cycle-scoped feasibility memo: claimants (and their gang-sim
        # members) with equal constraint specs share one predicate pass
        # over the node list — at 1k nodes x 16k claimants the
        # per-claimant pass WAS reclaim throughput (perf-multitenant
        # r4). Staleness rules live in FeasibilityMemo.
        memo = FeasibilityMemo(ssn)
        # Cycle-scoped per-queue exhausted-node memo (see the victim
        # scan below for the monotonicity argument). Gated on the
        # enabled reclaimable plugin set: only fns audited against the
        # contract at Session.add_reclaimable_fn may feed it.
        enabled_reclaimable = {
            plugin.name
            for tier in ssn.tiers
            for plugin in tier.plugins
            if bool(getattr(plugin, "enabled_reclaimable", False))
            and plugin.name in ssn.reclaimable_fns
        }
        memo_enabled = enabled_reclaimable <= MEMO_SAFE_RECLAIMABLE
        if not memo_enabled:
            logger.info(
                "reclaimable plugins %s outside the audited set %s; "
                "running without the exhausted-node memo",
                sorted(enabled_reclaimable), sorted(MEMO_SAFE_RECLAIMABLE),
            )
        no_victims: dict = {}

        while not queues.empty():
            queue = queues.pop()
            if ssn.overused(queue):
                continue
            jobs = preemptors_map.get(queue.uid)
            if jobs is None or jobs.empty():
                continue
            job = jobs.pop()
            tasks = preemptor_tasks.get(job.uid)
            if tasks is None or tasks.empty():
                continue
            task = tasks.pop()

            # One predicate pass per DISTINCT spec: the feasible-node
            # list feeds both the skip guard and the eviction scan.
            feasible = memo.feasible(task)

            # Deliberate divergence from reclaim.go: skip eviction when
            # free capacity already suffices — allocate, which runs after
            # reclaim in the default policy, will place this same cycle.
            # The reference lacks this guard and relies on slow
            # real-cluster pod deletion to not over-evict; with an
            # instant substrate it would drain the victim queue far
            # below its deserved share (its own e2e contract,
            # test/e2e/queue.go:26-69). The guard must be GANG-aware
            # and PACKING-aware: "this one task fits" (or "the aggregate
            # fits") is not enough — if the job still needs k members
            # for minAvailable and free capacity cannot hold all k
            # per-node, skipping would deadlock (partial gang
            # allocations never dispatch, so the same free capacity
            # re-appears every cycle while reclaim keeps skipping).
            # Simulate allocate's placement test (fits Idle → bind, else
            # fits Releasing → pipeline) with first-fit-decreasing over
            # the feasible nodes; skip eviction only when EVERY needed
            # gang member places. First-fit may fail where a smarter
            # packing succeeds — that errs toward evicting, which is the
            # reference's own behavior and self-corrects next cycle.
            needed = max(
                1,
                job.min_available
                - job.ready_task_num()
                - job.waiting_task_num(),
            )
            peeked = []
            while len(peeked) < needed - 1 and not tasks.empty():
                peeked.append(tasks.pop())
            for t in peeked:
                tasks.push(t)
            # Each member places only onto nodes ITS OWN predicates
            # accept — a heterogeneous gang (per-member selectors/
            # affinity/ports) must not be simulated onto nodes some
            # members cannot use, or the skip guard under-evicts every
            # cycle (the exact livelock it exists to prevent).
            #
            # The sim evaluates predicates against CURRENT node state
            # only; it cannot model member-vs-member interaction (two
            # members claiming the same host port, or inter-pod
            # (anti-)affinity among the gang itself — whose verdict also
            # depends on each pod's own labels, breaking the spec-keyed
            # memo below). When any member declares such a constraint,
            # skip the guard entirely and take the eviction path: erring
            # toward evicting is the reference's own behavior and
            # self-corrects next cycle, while erring toward skipping is
            # the livelock.
            def interacts(member):
                spec = member.pod.spec
                if any(c.ports for c in spec.containers):
                    return True
                aff = spec.affinity
                return aff is not None and bool(
                    aff.pod_affinity or aff.pod_anti_affinity
                )

            if any(interacts(m) for m in [task] + peeked):
                all_fit = False
            else:
                all_fit = self._sim_gang_fits(memo, task, peeked)
            if all_fit:
                queues.push(queue)
                continue

            assigned = False
            victims_evicted = 0
            exhausted = no_victims.setdefault(job.queue, set())
            for node in feasible:
                # Memo soundness: within a cycle, verdicts in the
                # default reclaim chain move DOWN on evictions
                # (proportion's per-queue over-deserved quota shrinks,
                # gang's minAvailable floors approach, conformance is
                # static), so a node that yielded zero victims stays
                # victimless — UNLESS a successful pipeline raises some
                # claimant queue's allocated above its deserved share,
                # which can newly expose THAT queue's running tasks as
                # victims. A pipeline of queue Q therefore invalidates
                # the memos of every claimant queue EXCEPT Q (Q's own
                # claimants reclaim from queues whose availability only
                # shrank). With a single starving queue — and in the
                # saturated stall phase, where a backlog of claimants
                # re-evaluated every floored job on every node each
                # wave (measured 1.17M evictable calls per cycle at 1k
                # nodes under a scattered placement) — the memo
                # persists exactly where it pays.
                if memo_enabled and node.name in exhausted:
                    continue  # see memo soundness note below
                resreq = task.init_resreq.clone()
                reclaimed = Resource.empty()

                # Candidates are the live node-task objects — the
                # reclaimable chain only filters (proportion/gang/
                # conformance read), so cloning every RUNNING task per
                # (claimant, node) pair (~18M clones per saturated 1k-
                # node cycle) buys nothing HERE. The clone happens at
                # EVICT time instead, and is load-bearing there:
                # session.evict flips the task's status before
                # node.update_task, and NodeInfo.remove_task derives the
                # removal delta from its stored task's CURRENT status —
                # evicting the node's own object would erase the
                # RUNNING→RELEASING capacity move, the claimant's
                # pipeline would miss the released capacity, and the
                # next cycle would evict again (observed as doubling
                # every reclaim wave). Reference analog: reclaim.go:96
                # clones at candidate-build time.
                reclaimees = []
                for t in node.tasks.values():
                    if t.status != TaskStatus.RUNNING:
                        continue
                    j = ssn.jobs.get(t.job)
                    if j is None:
                        continue
                    if j.queue != job.queue:
                        reclaimees.append(t)
                victims = ssn.reclaimable(task, reclaimees)
                if not victims:
                    if memo_enabled:
                        exhausted.add(node.name)
                    continue

                all_res = Resource.empty()
                for v in victims:
                    all_res.add(v.resreq)
                if all_res.less(resreq):
                    continue

                # Minimal victim prefix covering the claim, then ONE
                # batched eviction: bulk RELEASING moves per job +
                # aggregate deallocate handlers (Session.evict_batch)
                # instead of per-victim handler fan-out. Clone HERE
                # (see the candidate-build comment): the eviction must
                # not mutate the node's stored object before node
                # accounting reads its pre-evict status. Divergence
                # from the sequential loop only in the rare
                # evict-failure case: the per-task loop would try the
                # NEXT victim to make up the shortfall, the batch
                # settles for what succeeded and lets the next cycle
                # correct — the reference's own self-correction
                # contract (reclaim.go:173-180).
                chosen = []
                for reclaimee in victims:
                    chosen.append(reclaimee.clone())
                    reclaimed.add(reclaimee.resreq)
                    if resreq.less_equal(reclaimed):
                        break
                evicted = ssn.evict_batch(chosen, "reclaim")
                victims_evicted += len(evicted)
                if len(evicted) != len(chosen):
                    reclaimed = Resource.empty()
                    for t in evicted:
                        reclaimed.add(t.resreq)

                if task.init_resreq.less_equal(reclaimed):
                    try:
                        ssn.pipeline(task, node.name)
                        # The pipeline raised THIS queue's allocated —
                        # only other claimant queues' verdicts about
                        # its tasks can flip up (soundness note at the
                        # scan above).
                        for quid in list(no_victims):
                            if quid != job.queue:
                                del no_victims[quid]
                    except Exception:
                        # Corrected in next scheduling loop (reclaim.go:173-180)
                        logger.exception(
                            "Failed to pipeline Task <%s/%s> on <%s>",
                            task.namespace, task.name, node.name,
                        )
                    assigned = True
                    break

            # Victim-selection outcome for the claimant's next
            # unschedulable verdict (obs/explain).
            explain.note_victim_outcome(
                job.uid, "reclaim", victims_evicted, assigned
            )
            if assigned:
                queues.push(queue)


register_action(ReclaimAction())
