"""Multi-chip sharded solve: the production scale-out path.

The reference's only scale mechanism is a 16-goroutine fan-out over nodes
(reference util/scheduler_helper.go:84,137). The TPU-native analog shards
the NODE axis — the cluster-size scale axis — across a 1-D
``jax.sharding.Mesh``: every [T, N] intermediate (feasibility mask, score
matrix, bid keys) partitions by node shard, task-major vectors stay
replicated, and the global per-task argmax over nodes plus the assignment
scatter induce the cross-shard collectives, which XLA emits under GSPMD
(no hand-written collectives; they ride ICI on real hardware).

Used by ``actions/allocate_tpu`` when more than one device is visible and
by ``__graft_entry__.dryrun_multichip`` (the driver's multi-chip check).
"""

from __future__ import annotations

import functools
import os
from typing import Optional, Tuple

import jax
import jax.numpy as jnp
import numpy as np
from jax.sharding import Mesh, NamedSharding, PartitionSpec as P

from .kernels import PackedInputs, SolverInputs, solve, solve_auto, solve_staged

NODE_AXIS = "nodes"

# SolverInputs fields whose FIRST axis is the node axis.
_NODE_MAJOR = (
    "node_feas", "node_idle", "node_releasing", "node_cap",
    "node_task_count", "node_max_tasks",
)
# SolverInputs fields whose SECOND axis is the node axis ([G|P|S, N] rows).
_NODE_MINOR = ("group_feas", "pair_feas", "score_rows")
# PackedInputs stacks node tables as [k, N, ...]: node axis is axis 1.
_PACKED_NODE_MINOR = ("node_f32", "node_i32") + _NODE_MINOR


def _distributed_initialized() -> bool:
    """Version-tolerant "has jax.distributed.initialize already run"
    probe: jax >= 0.5 exposes ``is_initialized``; 0.4.x keeps the
    coordinator handle on the private distributed state (API drift the
    seed inherited — a missing probe here crashed every multi-host
    join attempt on 0.4.x with AttributeError)."""
    is_init = getattr(jax.distributed, "is_initialized", None)
    if is_init is not None:
        return bool(is_init())
    try:
        from jax._src.distributed import global_state

        return global_state.coordinator_address is not None
    except Exception:  # pragma: no cover - further private-API drift
        return False


def init_distributed(coordinator_address=None, num_processes=None,
                     process_id=None):
    """Join a multi-HOST jax runtime (DCN scale-out) before building the
    mesh. After this, ``jax.devices()`` spans every host's chips and
    ``default_mesh()``/``solve_sharded`` work unchanged — XLA lays intra-
    host collectives on ICI and inter-host legs on DCN under GSPMD; the
    solver code has no host awareness at all.

    SPMD contract: EVERY process of the distributed runtime must execute
    every sharded solve (jax multi-process collectives block until all
    participants arrive). This is therefore an API for symmetric solver
    deployments — e.g. a dedicated solver job whose replicas all call
    ``solve_sharded`` on identical inputs — NOT for scheduler replicas
    behind leader election, where only the leader would solve and the
    first collective would deadlock. The scheduler server deliberately
    does not auto-join a distributed runtime for that reason.

    Parameters default to the JAX_COORDINATOR_ADDRESS / JAX_NUM_PROCESSES
    / JAX_PROCESS_ID environment (the jax.distributed convention). No-op
    when no coordinator is configured (single-host mode)."""
    coordinator_address = coordinator_address or os.environ.get(
        "JAX_COORDINATOR_ADDRESS"
    )
    if not coordinator_address:
        return False
    # Idempotent: a retry path or second defensive join must not crash
    # (jax.distributed.initialize raises if called twice).
    if _distributed_initialized():
        return True
    if num_processes is None:
        env_n = os.environ.get("JAX_NUM_PROCESSES", "")
        num_processes = int(env_n) if env_n else None
    if process_id is None:
        env_id = os.environ.get("JAX_PROCESS_ID", "")
        process_id = int(env_id) if env_id else None
    jax.distributed.initialize(
        coordinator_address=coordinator_address,
        num_processes=num_processes,
        process_id=process_id,
    )
    return True


def default_mesh(devices=None):
    """A 1-D node-axis mesh over ``devices`` (default: all visible
    devices), or None when only one device exists (single-chip solves
    need no mesh)."""
    devices = jax.devices() if devices is None else list(devices)
    if len(devices) < 2:
        return None
    return Mesh(np.asarray(devices), (NODE_AXIS,))


def shardings_for(inputs, mesh: Mesh):
    """A pytree of NamedShardings matching ``inputs`` (SolverInputs or
    PackedInputs): node-axis fields partitioned over the mesh, everything
    else replicated."""
    rep = NamedSharding(mesh, P())
    major = NamedSharding(mesh, P(NODE_AXIS))
    minor = NamedSharding(mesh, P(None, NODE_AXIS))
    cls = type(inputs)

    def spec(f, sh):
        # Optional fields (candidate slabs on legacy bundles) may be
        # None; the sharding pytree must mirror that or device_put's
        # treedefs mismatch. Candidate slabs are class-row tables (node
        # IDS, not node columns), so they replicate.
        return None if getattr(inputs, f, None) is None else sh

    if isinstance(inputs, PackedInputs):
        return cls(**{
            f: spec(f, minor if f in _PACKED_NODE_MINOR else rep)
            for f in cls._fields
        })
    return cls(**{
        f: spec(
            f,
            major if f in _NODE_MAJOR
            else minor if f in _NODE_MINOR else rep,
        )
        for f in cls._fields
    })


def pad_nodes(inputs, multiple: int):
    """Pad the node axis up to a multiple of ``multiple`` so shards are
    even. Padded nodes are infeasible (node_feas False) and empty, so the
    solver can never assign to them; padded mask/score rows are
    False/zero.

    On the production path this is an identity: ``tensorize`` buckets the
    node axis to multiples of 256 (snapshot.py), divisible by any
    power-of-two mesh, so the eager pad ops below only run for raw
    unbucketed inputs (tests, tools)."""
    if isinstance(inputs, PackedInputs):
        n = inputs.node_f32.shape[1]
    else:
        n = inputs.node_idle.shape[0]
    pad = (-n) % multiple
    if pad == 0:
        return inputs

    def pad_axis(x, axis):
        widths = [(0, 0)] * x.ndim
        widths[axis] = (0, pad)
        return jnp.pad(x, widths)

    if isinstance(inputs, PackedInputs):
        return inputs._replace(**{
            f: pad_axis(getattr(inputs, f), 1) for f in _PACKED_NODE_MINOR
        })
    repl = {f: pad_axis(getattr(inputs, f), 0) for f in _NODE_MAJOR}
    repl.update(
        {f: pad_axis(getattr(inputs, f), 1) for f in _NODE_MINOR}
    )
    if getattr(inputs, "cand_idx", None) is not None:
        # Candidate slabs use an invalid-node sentinel >= N; after
        # padding, the old sentinel value would alias a (padded, empty)
        # REAL row, so move it past the new node count.
        repl["cand_idx"] = jnp.where(
            inputs.cand_idx >= n, n + pad, inputs.cand_idx
        )
    return inputs._replace(**repl)


def pad_tasks(inputs: SolverInputs, multiple: int) -> SolverInputs:
    """Pad the TASK axis of a SolverInputs bundle up to a multiple of
    ``multiple`` so the sharded sparse solve's row blocks are even.
    Padded rows are invalid (``task_valid`` False), carry no resources,
    isolated job ids, and INT_MAX ranks, so no solver path can act on
    them — callers slice ``assigned[:T]`` back.

    On the production path this is an identity for power-of-two
    meshes: ``tensorize`` buckets the task axis to multiples of
    256/2048 (snapshot._task_bucket)."""
    T = inputs.task_req.shape[0]
    pad = (-T) % multiple
    if pad == 0:
        return inputs

    def pad_axis0(x: jnp.ndarray) -> jnp.ndarray:
        widths = [(0, 0)] * x.ndim
        widths[0] = (0, pad)
        return jnp.pad(x, widths)

    repl = {
        f: pad_axis0(getattr(inputs, f))
        for f in (
            "task_req", "task_fit", "task_queue", "task_group",
            "task_valid",
        )
    }
    repl["task_rank"] = jnp.concatenate([
        jnp.asarray(inputs.task_rank),
        jnp.full((pad,), jnp.iinfo(jnp.int32).max, jnp.int32),
    ])
    # Isolated job ids: padded rows must never join a real job's
    # segment reductions.
    repl["task_job"] = jnp.concatenate([
        jnp.asarray(inputs.task_job),
        jnp.arange(T, T + pad, dtype=jnp.int32),
    ])
    if getattr(inputs, "task_cand", None) is not None:
        repl["task_cand"] = pad_axis0(inputs.task_cand)
    return inputs._replace(**repl)


# ---------------------------------------------------------------------------
# Sharded-sparse dispatch policy + layout tokens (PR 12).
# ---------------------------------------------------------------------------

# Below this task count the single-device sparse jit wins outright: the
# slab rounds do O(T·K) work with no [T, N] structures, and the sharded
# path pays two collectives per commit; the crossover mirrors the
# existing K·s<N rationale for keeping slab inputs off the dense mesh.
_SPARSE_SHARD_MIN_TASKS = 1 << 16
# Past this task count (and a >=4-device mesh) the per-commit
# collective cadence itself dominates and the policy moves to the
# two-level per-rack solve (collective-free local phase, one psum
# reconcile) — quality-approximate, so deliberately far past every
# parity-suite shape.
_TWO_LEVEL_MIN_TASKS = 1 << 19

# Forensics of the most recent solve_sharded dispatch (mode, shard
# count, engagement), read by actions.allocate_tpu for
# last_stats/metrics attribution. Single-threaded by construction,
# like device_cache.last_pack_stats.
last_dispatch: dict = {}

# Device count + rack-map digest witnessed by the first sharded
# dispatch — process-constant once set (a jax process cannot change its
# device set), and deliberately NEVER probed outside a solve path:
# jax.devices() on a wedged tunnel can hang, and warm-plan/native paths
# must not take that risk (see prospective_layout_token).
_layout_state: dict = {"devices": None, "rack": None}


def rack_perm(mesh: Mesh) -> np.ndarray:
    """Topology-aligned shard→rack map for the two-level solve:
    ``rack_perm(mesh)[shard]`` is the rack (node block) shard ``shard``
    owns. Backends that expose physical placement (TPU: ``slice_index``
    + ICI ``coords``) get racks ordered by (slice, coords) so each rack
    block lands on physically adjacent chips (Tesserae-style); backends
    without coordinates (CPU meshes, older runtimes) fall back to the
    contiguous identity map, which is exactly the pre-topology
    behavior."""
    devs = list(np.asarray(mesh.devices).flat)
    keys = []
    for d in devs:
        coords = getattr(d, "coords", None)
        if coords is None:
            return np.arange(len(devs), dtype=np.int32)
        slice_idx = getattr(d, "slice_index", None)
        keys.append((
            slice_idx if slice_idx is not None else 0, tuple(coords),
        ))
    order = sorted(range(len(devs)), key=lambda i: keys[i])
    perm = np.empty(len(devs), dtype=np.int32)
    for rack, shard in enumerate(order):
        perm[shard] = rack
    return perm


def rack_digest(mesh: Optional[Mesh] = None) -> Optional[str]:
    """Short content token of the mesh's rack map, carried in the
    layout tokens so BOTH the warm-start plan and the selection caches
    invalidate when the node→rack decomposition moves (a topology-
    aligned split reshuffles which node block each shard owns). The
    contiguous identity map hashes to a stable ``c<n>`` token; None
    when no mesh exists."""
    if mesh is None:
        mesh = default_mesh()
    if mesh is None:
        return None
    perm = rack_perm(mesh)
    if np.array_equal(perm, np.arange(len(perm), dtype=np.int32)):
        return f"c{len(perm)}"
    import hashlib

    return hashlib.blake2b(perm.tobytes(), digest_size=4).hexdigest()


def sparse_shard_mode(n_tasks: int, mesh: Optional[Mesh]) -> str:
    """Resolve the sharded-sparse dispatch mode for a snapshot:
    ``single`` (single-device sparse jit), ``flat`` (task-sharded
    shard_map, bit-equal to single), or ``two-level`` (per-rack solve +
    global reconciliation, quality-approximate). ``KBT_SPARSE_SHARD_MODE``
    forces a mode (``off``/``single``, ``flat``, ``two-level``); unset
    = the shape policy above."""
    if mesh is None or mesh.size < 2:
        return "single"
    raw = os.environ.get("KBT_SPARSE_SHARD_MODE", "").strip().lower()
    if raw in ("off", "single", "0", "disable", "disabled"):
        return "single"
    if raw in ("flat", "1", "force"):
        return "flat"
    if raw in ("two-level", "two_level", "2", "hierarchical"):
        return "two-level"
    if n_tasks < _SPARSE_SHARD_MIN_TASKS:
        return "single"
    if n_tasks >= _TWO_LEVEL_MIN_TASKS and mesh.size >= 4:
        return "two-level"
    return "flat"


def prospective_layout_token() -> Optional[str]:
    """The solver layout a solve dispatched NOW would run under, or
    None when no sharded dispatch has happened yet (device count
    unknown — probing it here could hang on a wedged backend, and a
    process that never solved on a device has no layout to drift
    from). Consumed by the warm-start plan: a token change voids
    carried verdicts with the labeled ``mesh-changed`` fallback."""
    n = _layout_state["devices"]
    if n is None:
        return None
    mode = os.environ.get("KBT_SPARSE_SHARD_MODE", "").strip().lower()
    token = f"{n}dev:{mode or 'auto'}"
    rack = _layout_state.get("rack")
    # Rack suffix only when the dispatch pinned a rack map — tokens
    # from pre-topology processes (saved warm states) keep comparing
    # equal to themselves.
    return f"{token}:{rack}" if rack else token


def packed_sparse_placement(n_tasks: int) -> Tuple[Optional[NamedSharding], str]:
    """Device placement + layout token for the packed snapshot
    (consumed by tensorize → device_cache.pack): when the sharded
    sparse path will run, resident buffers are uploaded REPLICATED on
    the mesh so the jitted shard_map step never re-lays them out per
    cycle; otherwise None (default single-device placement). The token
    keys the device cache's residency — a layout flip forces a full
    labeled re-upload."""
    mesh = default_mesh()
    size = mesh.size if mesh is not None else 1
    mode = sparse_shard_mode(n_tasks, mesh) if n_tasks else "single"
    token = f"{size}dev:{mode}"
    rack = rack_digest(mesh)
    if rack:
        # Rack-map changes must re-key device residency: a moved
        # node→rack split invalidates resident selection keys and the
        # packed buffers' layout assumptions together.
        token = f"{token}:{rack}"
    if mesh is None or mode == "single":
        return None, token
    return NamedSharding(mesh, P()), token


# Weakrefs to jitted GSPMD steps for the retrace census (see
# spmd._jitted_steps — weak so eviction still frees the executable).
_jitted_steps: list = []


@functools.lru_cache(maxsize=32)
def _sharded_step(mesh: Mesh, shardings, staged, max_rounds, tail_bucket):
    if staged is None:
        fn = solve_auto
    elif staged:
        fn = functools.partial(solve_staged, tail_bucket=tail_bucket)
    else:
        fn = solve
    # allow_pallas=False: pallas_call has no GSPMD partitioning rule, so
    # under a node-sharded mesh it would force XLA to gather the [T, N]
    # operands whole onto every device (or fail to lower) — the fused
    # kernel is a single-device optimization; the sharded path keeps the
    # jnp chain, which partitions cleanly.
    import weakref

    step = jax.jit(
        lambda x: fn(x, max_rounds=max_rounds, allow_pallas=False),
        in_shardings=(shardings,),
    )
    _jitted_steps.append(weakref.ref(step))
    return step


def _staged_for_shape(inputs, staged):
    """Resolve the ``staged=None`` shape dispatch (solve_auto's rule)
    statically so both sharded implementations pick the same solver."""
    if staged is not None:
        return staged
    from .kernels import _STAGED_MIN_NODES, _STAGED_MIN_TASKS

    if isinstance(inputs, PackedInputs):
        T, N = inputs.task_f32.shape[1], inputs.node_f32.shape[1]
    else:
        T, N = inputs.task_req.shape[0], inputs.node_idle.shape[0]
    return N >= _STAGED_MIN_NODES and T >= _STAGED_MIN_TASKS


def _slab_classes(inputs) -> int:
    """Candidate-class count of an inputs bundle (0 = dense)."""
    cand = getattr(inputs, "cand_idx", None)
    return int(cand.shape[0]) if cand is not None else 0


def _task_count(inputs) -> int:
    if isinstance(inputs, PackedInputs):
        return int(inputs.task_f32.shape[1])
    return int(inputs.task_req.shape[0])


def _node_count(inputs) -> int:
    if isinstance(inputs, PackedInputs):
        return int(inputs.node_f32.shape[1])
    return int(inputs.node_idle.shape[0])


def _note_dispatch(mode: str, shards: int, reason: str = None) -> None:
    last_dispatch.clear()
    last_dispatch.update(
        mode=mode,
        shards=shards,
        sparse_sharded=mode in ("flat", "two-level"),
    )
    if reason:
        last_dispatch["reason"] = reason
    # First dispatch pins the process's device count + rack-map digest
    # for the warm plan's layout token (jax is live here by
    # definition).
    if _layout_state["devices"] is None:
        _layout_state["devices"] = jax.device_count()
        _layout_state["rack"] = rack_digest()


def _sparse_sharded_step(inputs, mesh: Mesh, mode: str, max_rounds,
                         tail_bucket):
    """(step, device_inputs) for the task-sharded sparse solve: pad
    the task axis (and node axis for two-level) to the mesh multiple,
    device_put replicated, hand back the cached jitted step."""
    from .spmd import (
        _spmd_sparse_step,
        note_commit_stats,
        sparse_spmd_shardings_for,
    )

    note_commit_stats(inputs)
    if not isinstance(inputs, PackedInputs):
        inputs = pad_tasks(inputs, mesh.size)
        if mode == "two-level":
            inputs = pad_nodes(inputs, mesh.size)
    elif _task_count(inputs) % mesh.size or (
        mode == "two-level" and _node_count(inputs) % mesh.size
    ):
        # A silent mis-split would simply never solve the remainder
        # rows; refuse loudly (solve_sharded routes ragged packed
        # bundles to the single-device jit before ever getting here).
        raise ValueError(
            f"sparse sharded solve needs task{'/node' if mode == 'two-level' else ''} "
            f"axes divisible by the mesh size {mesh.size}"
        )
    inputs = jax.device_put(
        inputs, sparse_spmd_shardings_for(inputs, mesh)
    )
    step = _spmd_sparse_step(
        mesh, max_rounds, tail_bucket, mode == "two-level"
    )
    return step, inputs


def sharded_step(
    inputs,
    mesh: Mesh,
    max_rounds: int = 256,
    staged=None,
    tail_bucket: int = 3072,
    impl: str = "spmd",
):
    """Return ``(step_fn, device_inputs)``: inputs padded and device_put
    onto the mesh ONCE, plus the cached jitted step to run on them. Use
    this when solving the same snapshot repeatedly (benchmarks, re-solve
    loops) so the host→device transfer is not re-paid per call.

    ``impl='spmd'`` (default) is the hierarchical shard_map solver
    (solver/spmd.py): node columns sharded, node/queue tables
    replicated, per-commit communication limited to a two-[T]-vector
    all_gather. ``impl='gspmd'`` keeps the legacy auto-partitioned
    single-device program (collective-dominated at scale; retained for
    A/B and as the fallback surface). Candidate-slab inputs route to
    the task-sharded SPARSE step when the shape/mesh policy engages it
    (``impl='sparse'`` forces flat, ``'sparse-two-level'`` the
    hierarchical mode)."""
    sparse_mode = None
    if impl == "sparse":
        sparse_mode = "flat"          # forced: ALWAYS the bit-parity mode
    elif impl == "sparse-two-level":
        sparse_mode = "two-level"
    elif impl == "spmd" and staged is None and _slab_classes(inputs) > 0:
        mode = sparse_shard_mode(_task_count(inputs), mesh)
        ragged = isinstance(inputs, PackedInputs) and (
            _task_count(inputs) % mesh.size
            or (mode == "two-level" and _node_count(inputs) % mesh.size)
        )
        if mode != "single" and not ragged:
            # Ragged packed axes keep the pre-existing dense-sharded
            # behavior (same graceful shape handling as solve_sharded).
            sparse_mode = mode
    if sparse_mode is not None:
        return _sparse_sharded_step(
            inputs, mesh, sparse_mode, max_rounds, tail_bucket
        )
    inputs = pad_nodes(inputs, mesh.size)
    if impl == "spmd":
        from .spmd import _spmd_step, spmd_shardings_for

        shardings = spmd_shardings_for(inputs, mesh)
        inputs = jax.device_put(inputs, shardings)
        step = _spmd_step(
            mesh, _staged_for_shape(inputs, staged), max_rounds,
            tail_bucket,
        )
        return step, inputs
    shardings = shardings_for(inputs, mesh)
    inputs = jax.device_put(inputs, shardings)
    step = _sharded_step(mesh, shardings, staged, max_rounds, tail_bucket)
    return step, inputs


def solve_sharded(
    inputs,
    mesh: Mesh = None,
    max_rounds: int = 256,
    staged=None,
    tail_bucket: int = 3072,
    impl: str = "spmd",
    allow_pallas: bool = True,
):
    """Run the batched solve with the node axis sharded over ``mesh``.

    ``staged``: None dispatches by shape (like ``solve_auto``), True
    forces the staged solver, False the full-width one. Falls back to the
    single-device jitted path when no mesh is available. Same semantics
    and results as the single-device solve — sharding changes layout, not
    the program. ``impl`` selects the hierarchical shard_map solver
    (default) or the legacy GSPMD auto-partitioning (see
    :func:`sharded_step`).

    Candidate-sparsified inputs (topk slabs present) dispatch through
    :func:`sparse_shard_mode`: at parity-suite scale the single-device
    sparse jit wins outright (the slab rounds do O(T·K) work with no
    [T, N] structures — one device beats N/s-sharded dense whenever
    K·s < N), so ``single`` stays the small-shape default; past the
    policy floor the task-sharded shard_map sparse solve (bit-equal
    ``flat``, or the Tesserae-style ``two-level``) takes over.
    ``KBT_SPARSE_SHARD_MODE`` forces a mode. The dense SPMD solvers
    remain the dense scale path.
    """
    if mesh is None:
        mesh = default_mesh()
    noted = False
    if mesh is not None and staged is None:
        # Shape probe only — no unpack() (its eager per-field slices
        # cost real milliseconds outside a jit).
        if _slab_classes(inputs) > 0:
            T = _task_count(inputs)
            mode = sparse_shard_mode(T, mesh)
            reason = None
            if mode != "single" and isinstance(inputs, PackedInputs):
                # A packed bundle cannot be re-padded without defeating
                # device residency; production buckets divide every
                # pow2 mesh, so ragged axes are a test/tool corner —
                # fall back to the single-device jit, labeled.
                if T % mesh.size or (
                    mode == "two-level"
                    and _node_count(inputs) % mesh.size
                ):
                    mode, reason = "single", "ragged-axes"
            _note_dispatch(mode, mesh.size, reason)
            noted = True
            if mode != "single":
                step, dev_inputs = _sparse_sharded_step(
                    inputs, mesh, mode, max_rounds, tail_bucket
                )
                result = step(dev_inputs)
                if int(result.assigned.shape[0]) != T:
                    result = result._replace(
                        assigned=result.assigned[:T]
                    )
                return result
            mesh = None
    if mesh is None:
        if not noted:
            _note_dispatch("single", 1)
        # Single device: reuse the module-level cached jits.
        from .kernels import solve_full_jit, solve_jit, solve_staged_jit

        if staged is None:
            return solve_jit(
                inputs, max_rounds=max_rounds, allow_pallas=allow_pallas
            )
        if staged:
            return solve_staged_jit(
                inputs, max_rounds=max_rounds, tail_bucket=tail_bucket,
                allow_pallas=allow_pallas,
            )
        return solve_full_jit(
            inputs, max_rounds=max_rounds, allow_pallas=allow_pallas
        )

    _note_dispatch(f"dense-{impl}", mesh.size)
    step, inputs = sharded_step(
        inputs, mesh, max_rounds=max_rounds, staged=staged,
        tail_bucket=tail_bucket, impl=impl,
    )
    return step(inputs)
