"""Heap-backed priority queue ordered by a LessFn.

Mirrors reference pkg/scheduler/util/priority_queue.go:26-79. Items for which
``less_fn(a, b)`` is True pop first. Insertion order breaks ties (stable).
"""

from __future__ import annotations

import heapq
import itertools
from typing import Any, Callable, List

LessFn = Callable[[Any, Any], bool]


class _Entry:
    __slots__ = ("item", "less_fn", "seq")

    def __init__(self, item, less_fn, seq):
        self.item = item
        self.less_fn = less_fn
        self.seq = seq

    def __lt__(self, other: "_Entry") -> bool:
        if self.less_fn(self.item, other.item):
            return True
        if self.less_fn(other.item, self.item):
            return False
        return self.seq < other.seq


class PriorityQueue:
    def __init__(self, less_fn: LessFn):
        self._less_fn = less_fn
        self._heap: List[_Entry] = []
        self._seq = itertools.count()

    def push(self, item: Any) -> None:
        heapq.heappush(self._heap, _Entry(item, self._less_fn, next(self._seq)))

    def pop(self) -> Any:
        if not self._heap:
            return None
        return heapq.heappop(self._heap).item

    def empty(self) -> bool:
        return not self._heap

    def __len__(self) -> int:
        return len(self._heap)
