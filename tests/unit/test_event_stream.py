"""Event-stream hardening (doc/design/robustness.md): the ingest
guards (duplicate/stale/reorder absorption, gap detection), the
rate-limited gap-repair relist through the drain seam, the typed
cluster-error taxonomy + deterministic retry, and the delete-handler
idempotency regressions."""

import pytest

from kube_batch_tpu.api import PodPhase, TaskStatus, build_resource_list
from kube_batch_tpu.cache import SchedulerCache
from kube_batch_tpu.cluster import InProcessCluster
from kube_batch_tpu.cluster.errors import (
    ClusterAPIError,
    ObjectGoneError,
    TerminalClusterError,
    TransientClusterError,
    backoff_delay,
    deterministic_jitter,
    retry_transient,
)
from kube_batch_tpu.utils.test_utils import (
    FakeBinder,
    FakeEvictor,
    FakeStatusUpdater,
    FakeVolumeBinder,
    build_node,
    build_pod,
    build_pod_group,
    build_queue,
)


def req(cpu="1000m", mem="1Gi"):
    return dict(build_resource_list(cpu=cpu, memory=mem))


def make_cluster_cache():
    cluster = InProcessCluster(simulate_kubelet=True)
    cache = SchedulerCache(
        cluster=cluster,
        scheduler_name="tpu-batch",
        binder=FakeBinder(),
        evictor=FakeEvictor(),
        status_updater=FakeStatusUpdater(),
        volume_binder=FakeVolumeBinder(),
    )
    cache.start_ingest()
    return cluster, cache


def make_pod(name, node="", phase=PodPhase.PENDING, group="g1"):
    pod = build_pod("ns", name, node, phase, req(), group_name=group)
    pod.spec.scheduler_name = "tpu-batch"
    return pod


# ------------------------------------------------------------- guards


class TestIngestGuards:
    def test_duplicate_delivery_absorbed(self):
        cluster, cache = make_cluster_cache()
        pod = make_pod("p1")
        cluster.create_pod(pod)
        rv = pod.metadata.resource_version
        assert rv > 0
        before = dict(cache.integrity_state()["event_anomalies"])
        cache._on_watch_event("Pod", "ADDED", pod, rv)
        anomalies = cache.integrity_state()["event_anomalies"]
        assert anomalies.get("duplicate", 0) == before.get(
            "duplicate", 0
        ) + 1
        # Mirror unchanged: still exactly one task.
        assert sum(len(j.tasks) for j in cache.jobs.values()) == 1
        cache.shutdown()

    def test_stale_delivery_never_regresses(self):
        cluster, cache = make_cluster_cache()
        cluster.create_node(build_node(
            "n1", build_resource_list(cpu="4", memory="8Gi", pods=110)
        ))
        pod = make_pod("p1")
        cluster.create_pod(pod)
        cluster.bind_pod(pod, "n1")  # MODIFIED with a newer rv
        job = next(iter(cache.jobs.values()))
        task = next(iter(job.tasks.values()))
        assert task.node_name == "n1"
        # Redeliver with an OLDER rv (the bind-confirm's predecessor):
        # the guard must skip it — the shared object's current content
        # would be re-applied harmlessly here, but on a real cluster a
        # stale event carries stale content.
        cache._on_watch_event(
            "Pod", "MODIFIED", pod, pod.metadata.resource_version - 1
        )
        anomalies = cache.integrity_state()["event_anomalies"]
        assert anomalies.get("stale", 0) >= 1
        task = next(iter(job.tasks.values()))
        assert task.node_name == "n1"
        cache.shutdown()

    def test_reorder_fills_hole_without_gap(self):
        cluster, cache = make_cluster_cache()
        p1, p2 = make_pod("p1"), make_pod("p2")
        # Deliver out of order by hand: stamp rvs via the cluster but
        # suppress delivery, then feed the cache swapped.
        cluster.remove_watch(cache._on_watch_event)
        cluster.create_pod(p1)
        cluster.create_pod(p2)
        cluster.add_watch(cache._on_watch_event)
        cache._on_watch_event(
            "Pod", "ADDED", p2, p2.metadata.resource_version
        )
        assert cache.integrity_state()["stream_missing"] >= 1
        cache._on_watch_event(
            "Pod", "ADDED", p1, p1.metadata.resource_version
        )
        state = cache.integrity_state()
        assert state["stream_missing"] == 0
        assert state["event_anomalies"].get("reorder", 0) == 1
        # Both pods landed; no gap, no relist.
        assert sum(len(j.tasks) for j in cache.jobs.values()) == 2
        cache.drain_resync_queue()
        cache.drain_resync_queue()
        assert cache.integrity_state()["relists"]["ok"] == 0
        cache.shutdown()

    def test_dropped_event_confirms_gap_and_relists(self):
        cluster, cache = make_cluster_cache()
        cache._relist_min_interval = 0.0
        cluster.create_pod(make_pod("p0"))
        # Drop p1's ADD entirely; a later event exposes the hole.
        cluster.remove_watch(cache._on_watch_event)
        p1 = make_pod("p1")
        cluster.create_pod(p1)
        cluster.add_watch(cache._on_watch_event)
        cluster.create_pod(make_pod("p2"))
        assert sum(len(j.tasks) for j in cache.jobs.values()) == 2
        # Two checkpoints confirm the persistent hole → relist repairs.
        worked = [cache.drain_resync_queue() for _ in range(3)]
        state = cache.integrity_state()
        assert state["event_anomalies"].get("gap", 0) == 1
        assert state["relists"]["ok"] == 1
        assert state["divergence_repaired"].get("missed-pod", 0) == 1
        assert sum(len(j.tasks) for j in cache.jobs.values()) == 3
        assert any(worked), worked
        cache.shutdown()

    def test_relist_rate_limited_on_injected_clock(self):
        cluster, cache = make_cluster_cache()
        now = [0.0]
        cache._relist_clock = lambda: now[0]
        cache._relist_min_interval = 5.0

        def drop_one(name):
            cluster.remove_watch(cache._on_watch_event)
            cluster.create_pod(make_pod(name))
            cluster.add_watch(cache._on_watch_event)
            cluster.create_pod(make_pod(f"{name}-wit"))

        drop_one("pa")
        for _ in range(3):
            cache.drain_resync_queue()
        assert cache.integrity_state()["relists"]["ok"] == 1
        # A second gap inside the window: relist stays pending.
        drop_one("pb")
        for _ in range(3):
            cache.drain_resync_queue()
        state = cache.integrity_state()
        assert state["relists"]["ok"] == 1
        assert state["relist_pending"] is True
        # Window passes → the pending relist runs.
        now[0] = 6.0
        cache.drain_resync_queue()
        state = cache.integrity_state()
        assert state["relists"]["ok"] == 2
        assert state["relist_pending"] is False
        cache.shutdown()

    def test_rvless_events_bypass_guards(self):
        """Direct handler feeding (the whole existing test corpus)
        never engages the guards."""
        cache = SchedulerCache(
            binder=FakeBinder(), evictor=FakeEvictor(),
            status_updater=FakeStatusUpdater(),
            volume_binder=FakeVolumeBinder(),
        )
        pod = make_pod("p1")
        cache.add_pod(pod)
        cache.add_pod(pod)  # idempotent, no anomaly counted
        assert cache.integrity_state()["event_anomalies"] == {}
        cache.shutdown()


# ---------------------------------------------------- delete idempotency


class TestDeleteIdempotency:
    def test_double_delete_pod_running(self):
        """Satellite regression: duplicate delete_pod must not
        double-credit node capacity or escape a KeyError."""
        cache = SchedulerCache(
            binder=FakeBinder(), evictor=FakeEvictor(),
            status_updater=FakeStatusUpdater(),
            volume_binder=FakeVolumeBinder(),
        )
        cache.add_node(build_node(
            "n1", build_resource_list(cpu="4", memory="8Gi", pods=110)
        ))
        pod = make_pod("p1", node="n1", phase=PodPhase.RUNNING)
        cache.add_pod(pod)
        ni = cache.nodes["n1"]
        idle0 = ni.idle.clone()
        idle0.add(ni.used)
        cache.delete_pod(pod)
        after1 = (ni.idle.milli_cpu, ni.used.milli_cpu)
        cache.delete_pod(pod)
        after2 = (ni.idle.milli_cpu, ni.used.milli_cpu)
        assert after1 == after2
        assert ni.idle.milli_cpu == idle0.milli_cpu
        assert ni.used.is_empty()
        cache.shutdown()

    def test_double_delete_pod_releasing(self):
        cache = SchedulerCache(
            binder=FakeBinder(), evictor=FakeEvictor(),
            status_updater=FakeStatusUpdater(),
            volume_binder=FakeVolumeBinder(),
        )
        cache.add_node(build_node(
            "n1", build_resource_list(cpu="4", memory="8Gi", pods=110)
        ))
        pod = make_pod("p1", node="n1", phase=PodPhase.RUNNING)
        cache.add_pod(pod)
        job = next(iter(cache.jobs.values()))
        task = next(iter(job.tasks.values()))
        job.update_task_status(task, TaskStatus.RELEASING)
        cache.nodes["n1"].update_task(task)
        cache.delete_pod(pod)
        ni = cache.nodes["n1"]
        releasing1 = ni.releasing.milli_cpu
        cache.delete_pod(pod)
        assert ni.releasing.milli_cpu == releasing1 == 0.0
        assert ni.used.is_empty()
        cache.shutdown()

    def test_double_delete_node(self):
        cache = SchedulerCache(
            binder=FakeBinder(), evictor=FakeEvictor(),
            status_updater=FakeStatusUpdater(),
            volume_binder=FakeVolumeBinder(),
        )
        node = build_node(
            "n1", build_resource_list(cpu="4", memory="8Gi", pods=110)
        )
        cache.add_node(node)
        cache.delete_node(node)
        cache.delete_node(node)  # must not raise
        assert "n1" not in cache.nodes
        cache.shutdown()

    def test_update_task_tolerates_missing_old(self):
        """A reconcile update of a task the mirror no longer holds must
        ADD the new state, not raise — the KeyError used to spin the
        resync queue until the terminal cap."""
        cluster, cache = make_cluster_cache()
        pod = make_pod("p1")
        cluster.create_pod(pod)
        job = next(iter(cache.jobs.values()))
        task = next(iter(job.tasks.values())).clone()
        cache.delete_pod(pod)          # mirror entry gone
        cluster.create_pod(pod)        # truth has it again (recreate)
        cache._sync_task(task)         # must not raise
        assert sum(len(j.tasks) for j in cache.jobs.values()) == 1
        cache.shutdown()


# --------------------------------------------------------- typed retry


class TestTypedRetry:
    def test_taxonomy(self):
        assert issubclass(TransientClusterError, ClusterAPIError)
        assert issubclass(ObjectGoneError, TerminalClusterError)

    def test_retries_transient_then_succeeds(self):
        calls = []

        def op():
            calls.append(1)
            if len(calls) < 3:
                raise TransientClusterError("blip")
            return "ok"

        slept = []
        assert retry_transient(
            op, attempts=4, base=0.01, cap=0.1, salt="t",
            sleep=slept.append,
        ) == "ok"
        assert len(calls) == 3
        assert len(slept) == 2
        # Deterministic jitter: same salt+attempt → same delay.
        assert slept[0] == backoff_delay(0, 0.01, 0.1, "t")

    def test_terminal_surfaces_immediately(self):
        calls = []

        def op():
            calls.append(1)
            raise TerminalClusterError("schema")

        with pytest.raises(TerminalClusterError):
            retry_transient(op, attempts=4, sleep=lambda _d: None)
        assert len(calls) == 1

    def test_exhausted_raises_last(self):
        def op():
            raise TransientClusterError("still down")

        with pytest.raises(TransientClusterError):
            retry_transient(op, attempts=3, sleep=lambda _d: None)

    def test_jitter_deterministic_and_spread(self):
        a = deterministic_jitter("x", 0)
        assert a == deterministic_jitter("x", 0)
        assert a != deterministic_jitter("x", 1)
        assert 0.0 <= a < 1.0

    def test_sync_task_classifies_gone_as_delete(self):
        cluster, cache = make_cluster_cache()
        pod = make_pod("p1")
        cluster.create_pod(pod)
        job = next(iter(cache.jobs.values()))
        task = next(iter(job.tasks.values())).clone()

        def gone(_ns, _name):
            raise ObjectGoneError("404")

        cache.cluster.get_pod = gone
        cache._sync_task(task)
        assert sum(len(j.tasks) for j in cache.jobs.values()) == 0
        cache.shutdown()


# ----------------------------------------- drain ordering (satellite 3)


class TestDrainInterleaving:
    def _cluster_with_job(self):
        cluster, cache = make_cluster_cache()
        cluster.create_node(build_node(
            "n1", build_resource_list(cpu="8", memory="16Gi", pods=110)
        ))
        cluster.create_queue(build_queue("default"))
        cluster.create_pod_group(build_pod_group(
            "g1", namespace="ns", min_member=1
        ))
        return cluster, cache

    def test_reordered_resync_items_drain_deterministically(self):
        """Items enqueued in two different orders drain to the same end
        state (the drain sorts)."""
        cluster, cache = self._cluster_with_job()
        pods = [make_pod(f"p{i}") for i in range(4)]
        for pod in pods:
            cluster.create_pod(pod)
        tasks = sorted(
            (t.clone() for j in cache.jobs.values()
             for t in j.tasks.values()),
            key=lambda t: t.name,
        )
        for order in (tasks, list(reversed(tasks))):
            for t in order:
                cache._resync_task(t.clone())
            synced = cache.drain_resync_queue()
            assert synced >= len(tasks)
            assert sum(
                len(j.tasks) for j in cache.jobs.values()
            ) == len(pods)
        cache.shutdown()

    def test_interleaved_resync_and_cleanup_drains(self):
        """Cleanup and resync queues drained in interleaved orders
        converge: the terminated job is removed exactly once, resync
        of its dead task reconciles as a delete."""
        cluster, cache = self._cluster_with_job()
        pod = make_pod("p1")
        cluster.create_pod(pod)
        job = next(iter(cache.jobs.values()))
        task = next(iter(job.tasks.values())).clone()
        # Terminate: pod succeeded then deleted from the cluster.
        pod.status.phase = PodPhase.SUCCEEDED
        cluster.update("Pod", pod)
        cluster.delete_pod(pod)
        # Interleave: resync of the dead task queued BETWEEN two
        # cleanup passes, plus a cleanup queued after the resync.
        cache._queue_job_cleanup(job)
        cache.drain_cleanup_queue()
        cache._resync_task(task.clone())
        cache._queue_job_cleanup(job)
        assert cache.drain_resync_queue() >= 1
        cache.drain_cleanup_queue()
        assert all(
            not j.tasks for j in cache.jobs.values()
        ), cache.jobs
        cache.shutdown()

    def test_gap_work_counts_toward_drain_quiescence(self):
        """A pending gap keeps drain_resync_queue reporting progress so
        settle loops don't exit before the relist ran."""
        cluster, cache = make_cluster_cache()
        cache._relist_min_interval = 0.0
        cluster.remove_watch(cache._on_watch_event)
        cluster.create_pod(make_pod("px"))
        cluster.add_watch(cache._on_watch_event)
        cluster.create_pod(make_pod("py"))
        results = []
        for _ in range(4):
            results.append(cache.drain_resync_queue())
            if results[-1] == 0:
                break
        assert cache.integrity_state()["relists"]["ok"] == 1
        assert results[-1] == 0  # quiescent at the end
        assert any(results), results
        cache.shutdown()
