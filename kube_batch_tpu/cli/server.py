"""Server runner: metrics endpoint, leader election, scheduler lifecycle.

Mirrors reference cmd/kube-batch/app/server.go (:63 Run — build config,
start scheduler, /metrics HTTP server :86-89, leader election via resource
lock :96-141). Standalone substitutions: the cluster substrate is the
in-process store (or a YAML-loaded snapshot of one), and the leader lock is
a lease file in the lock namespace directory — same lease/renew/retry
timings as the reference's ConfigMap lock (server.go:49-53).
"""

from __future__ import annotations

import json
import logging
import os
import threading
import time
from http.server import BaseHTTPRequestHandler, ThreadingHTTPServer
from typing import Optional, Tuple

from .. import metrics
from ..cache import new_scheduler_cache
from ..cluster import ClusterAPI, InProcessCluster
from ..obs import QUALITY, RECORDER, TELEMETRY
from ..obs import explain as obs_explain
from ..obs import latency as obs_latency
from ..obs import telemetry as obs_telemetry
from ..scheduler import Scheduler
from ..version import RELEASE_VERSION
from .options import (
    LEASE_DURATION,
    RENEW_DEADLINE,
    RETRY_PERIOD,
    ServerOption,
    register_options,
)
from .state import load_cluster_state

logger = logging.getLogger(__name__)


class _MetricsHandler(BaseHTTPRequestHandler):
    """Serves /metrics (Prometheus text exposition, reference
    server.go:86-89 promhttp handler) plus the observability surface:

    - ``/healthz``: cheap liveness ("ok") — probes must not scrape the
      full exposition;
    - ``/debug/vars``: uptime, version, last-cycle age, cycle error
      count, plus a resource-health snapshot (process RSS, allocator
      blocks, JAX device memory and live buffers, jit cache sizes,
      telemetry ring occupancy) as one small JSON object — one curl
      answers "is this process healthy";
    - ``/debug/timeseries``: the long-horizon telemetry windows + the
      newest raw per-cycle samples (obs/telemetry.py);
    - ``/debug/flightrecorder``: the flight recorder's ring as
      canonical JSON (obs/flightrecorder.py);
    - ``/debug/latency``: the placement-latency ledger snapshot —
      per-queue/per-cycle-kind stage-decomposed percentiles, recent
      applied entries, audit-ring meta (obs/latency.py);
    - ``/debug/quality``: the placement-quality monitor snapshot —
      the newest scorecard (density/fragmentation/fairness/churn)
      plus the cumulative churn counters (obs/quality.py);
    - ``/debug/jobs`` and ``/debug/jobs/<ns>/<name>``: per-job last
      unschedulable verdicts (obs/explain.py).

    Unknown paths get a 404 WITH a body naming the path — a silent
    empty 404 reads like a transport bug from curl."""

    def _reply(self, body, ctype="text/plain", code=200):
        if isinstance(body, str):
            body = body.encode()
        self.send_response(code)
        self.send_header("Content-Type", ctype)
        self.send_header("Content-Length", str(len(body)))
        self.end_headers()
        self.wfile.write(body)

    def _debug_vars(self) -> dict:
        now = time.time()
        last = RECORDER.last_cycle_ts
        out = {
            "version": RELEASE_VERSION,
            "pid": os.getpid(),
            "uptime_seconds": round(now - _SERVER_STARTED[0], 3),
            "last_cycle_age_seconds": (
                round(now - last, 3) if last is not None else None
            ),
            "cycles_recorded": RECORDER._seq,
            "cycle_errors": metrics.scheduler_cycle_errors.get(),
            "unschedulable_jobs": len(obs_explain.all_verdicts()),
            "telemetry": {
                "cycles_observed": TELEMETRY.cycles_observed,
                "windows_rolled": TELEMETRY.windows_rolled,
                "window_cycles": TELEMETRY.window_cycles,
                "ring_occupancy": len(TELEMETRY._raw),
            },
        }
        # Resource-watermark snapshot: same probes the telemetry series
        # record (RSS, allocator blocks, jax device memory / live
        # buffers, jit cache sizes, ring occupancies, label-series
        # cardinality) — a single curl gives a health picture.
        try:
            out["watermarks"] = obs_telemetry.collect_watermarks(
                cache=TELEMETRY.attached_cache()
            )
        except Exception:  # pragma: no cover - probes must not 500
            logger.exception("/debug/vars watermark probe failed")
        # Placement-latency SLI summary (obs/latency.py): stamped/
        # applied counters, stage and per-queue p99s, audit-ring meta —
        # one curl answers "are pods placing, and how fast". The full
        # percentile tree lives at /debug/latency.
        try:
            out["latency"] = {
                **obs_latency.LEDGER.summary(),
                "audit": obs_latency.AUDIT.meta(),
            }
        except Exception:  # pragma: no cover - probes must not 500
            logger.exception("/debug/vars latency probe failed")
        # Serving SLO surface (doc/design/serving.md): per-class
        # attainment, violation count, budget burn, pending targeted
        # placements — one curl answers "are serving SLOs being met".
        # A duplicate of latency.serving at the top level so SLO health
        # is greppable next to robustness/integrity.
        try:
            out["serving"] = obs_latency.LEDGER.serving_summary()
        except Exception:  # pragma: no cover - probes must not 500
            logger.exception("/debug/vars serving probe failed")
        # Degraded-mode surface (doc/design/robustness.md): breaker
        # state machine + quarantine age, the last ladder descent, the
        # loop watchdog, and the leadership fence — one curl says
        # whether (and why) the scheduler is running on a lower rung.
        try:
            from ..cache import recovery as cache_recovery
            from ..scheduler import ACTIVE_WATCHDOG, LEASE_TTL_CHECK
            from ..solver import containment

            cache = TELEMETRY.attached_cache()
            fence_fn = getattr(cache, "fence_reason", None)
            out["robustness"] = {
                "breaker": containment.BREAKER.state_dict(),
                "last_fallback": (
                    dict(containment.last_fallback) or None
                ),
                "solve_budget_seconds": containment.solve_budget(),
                "watchdog": (
                    ACTIVE_WATCHDOG.state_dict()
                    if ACTIVE_WATCHDOG is not None else None
                ),
                "watchdog_trips": metrics.scheduler_watchdog_trips.get(),
                "cache_fence": fence_fn() if fence_fn else None,
                # Failover surface: the startup journal-recovery pass's
                # outcome (None = clean start / no journal seam) and
                # the lease-TTL sanity verdict vs the watchdog budget.
                "recovery": cache_recovery.LAST_RECOVERY,
                "lease_ttl": LEASE_TTL_CHECK,
            }
        except Exception:  # pragma: no cover - probes must not 500
            logger.exception("/debug/vars robustness probe failed")
        # Placement-quality surface (doc/design/quality.md): headline
        # numbers off the newest scorecard (packing density, Jain
        # fairness, emptiable nodes, churn per placement) plus the
        # cumulative disruption counters — one curl answers "is the
        # scheduler placing WELL, not just fast". The full card lives
        # at /debug/quality.
        try:
            snap = QUALITY.snapshot()
            last = snap.get("last") or {}
            out["quality"] = {
                "enabled": snap["enabled"],
                "every": snap["every"],
                "cards_computed": snap["cards_computed"],
                "counters": snap["counters"],
                "density_dom": last.get("density_dom"),
                "fairness_jain": (
                    last.get("fairness", {}).get("jain")
                    if last else None
                ),
                "emptiable_nodes": (
                    last.get("frag", {}).get("emptiable_nodes")
                    if last else None
                ),
                "churn_per_placement": (
                    last.get("churn", {}).get("per_placement")
                    if last else None
                ),
            }
        except Exception:  # pragma: no cover - probes must not 500
            logger.exception("/debug/vars quality probe failed")
        # State-integrity surface (doc/design/robustness.md, cluster-
        # truth anti-entropy): absorbed event-stream anomalies, watch-
        # gap/relist state, and the divergence sweep's cumulative
        # detected/repaired counters — one curl answers "does the
        # mirror still match the cluster, and what repaired it".
        try:
            cache = TELEMETRY.attached_cache()
            integrity_fn = getattr(cache, "integrity_state", None)
            out["integrity"] = integrity_fn() if integrity_fn else None
        except Exception:  # pragma: no cover - probes must not 500
            logger.exception("/debug/vars integrity probe failed")
        return out

    def do_GET(self):  # noqa: N802 (http.server API)
        path = self.path.split("?", 1)[0].rstrip("/")
        if path in ("", "/healthz"):
            self._reply("ok\n")
        elif path.startswith("/metrics"):
            self._reply(
                metrics.REGISTRY.expose_text(),
                ctype="text/plain; version=0.0.4",
            )
        elif path == "/debug/vars":
            self._reply(
                json.dumps(self._debug_vars(), sort_keys=True) + "\n",
                ctype="application/json",
            )
        elif path == "/debug/timeseries":
            self._reply(
                json.dumps(
                    TELEMETRY.snapshot(), sort_keys=True, default=repr
                ) + "\n",
                ctype="application/json",
            )
        elif path == "/debug/flightrecorder":
            self._reply(
                RECORDER.dump_json(reason="http") + "\n",
                ctype="application/json",
            )
        elif path == "/debug/latency":
            payload = obs_latency.LEDGER.snapshot()
            payload["audit"] = obs_latency.AUDIT.meta()
            self._reply(
                json.dumps(payload, sort_keys=True, default=repr) + "\n",
                ctype="application/json",
            )
        elif path == "/debug/quality":
            self._reply(
                json.dumps(
                    QUALITY.snapshot(), sort_keys=True, default=repr
                ) + "\n",
                ctype="application/json",
            )
        elif path == "/debug/jobs":
            payload = {
                "jobs": [v.to_dict() for v in obs_explain.all_verdicts()]
            }
            self._reply(
                json.dumps(payload, sort_keys=True) + "\n",
                ctype="application/json",
            )
        elif path.startswith("/debug/jobs/"):
            uid = path[len("/debug/jobs/"):]
            verdict = obs_explain.get_verdict(uid)
            if verdict is None:
                self._reply(
                    f"no unschedulable verdict recorded for job "
                    f"{uid!r}\n",
                    code=404,
                )
            else:
                self._reply(
                    json.dumps(
                        {"verdict": verdict.to_dict()}, sort_keys=True
                    ) + "\n",
                    ctype="application/json",
                )
        else:
            self._reply(f"404 page not found: {self.path}\n", code=404)

    def log_message(self, fmt, *args):
        logger.debug("metrics-http: " + fmt, *args)


# Wall-clock epoch of the most recent start_metrics_server call (list so
# the handler reads the live value; /debug/vars uptime).
_SERVER_STARTED = [time.time()]


def start_metrics_server(listen_address: str) -> Tuple[ThreadingHTTPServer, threading.Thread]:
    """Start the /metrics endpoint in a daemon thread; returns (server, thread)."""
    host, _, port = listen_address.rpartition(":")
    _SERVER_STARTED[0] = time.time()
    server = ThreadingHTTPServer((host or "0.0.0.0", int(port)), _MetricsHandler)
    thread = threading.Thread(target=server.serve_forever, daemon=True,
                              name="metrics-http")
    thread.start()
    return server, thread


class LeaderElector:
    """File-lease leader election.

    The reference locks a ConfigMap via resourcelock + leaderelection
    (server.go:96-141, lease 15s / renew 10s / retry 5s). Standalone analog:
    an O_EXCL-created lease file carrying {holder, renew_ts}; a lease whose
    renew timestamp is older than the lease duration may be stolen. Same
    timings, same semantics: winner runs, loser retries; losing the lease
    mid-flight calls on_stopped_leading (the reference fatals there,
    server.go:133).
    """

    def __init__(
        self,
        lock_dir: str,
        identity: str,
        lease_duration: float = LEASE_DURATION,
        renew_deadline: float = RENEW_DEADLINE,
        retry_period: float = RETRY_PERIOD,
    ):
        self.lock_path = os.path.join(lock_dir, "tpu-batch-leader.lock")
        self.identity = identity
        self.lease_duration = lease_duration
        self.renew_deadline = renew_deadline
        self.retry_period = retry_period
        self._renew_thread: Optional[threading.Thread] = None
        self._stop = threading.Event()
        self.is_leader = False
        self._lost: Optional[threading.Event] = None
        self.fenced_reason: Optional[str] = None

    def fence(self, reason: str = "") -> None:
        """Zombie-leader fencing (called by the loop watchdog via
        ``Scheduler.fence_hooks``): this process believes it is wedged,
        so it must STOP renewing and release the lease — otherwise the
        renew thread, which is perfectly healthy, keeps the cluster
        hostage to a leader that makes no progress. Signals the lost
        event too, so anything chained on leadership loss (the
        scheduling loop's stop) fires when the process unwedges."""
        self.fenced_reason = reason or "fenced"
        logger.error(
            "leader election FENCED (%s): releasing lease, no further "
            "renewals", self.fenced_reason,
        )
        self.is_leader = False
        if self._lost is not None:
            self._lost.set()
        self.release()

    def _read_lease(self):
        try:
            with open(self.lock_path) as f:
                return json.load(f)
        except (OSError, ValueError):
            return None

    def _write_lease(self) -> None:
        tmp = f"{self.lock_path}.{self.identity}.tmp"
        with open(tmp, "w") as f:
            json.dump({"holder": self.identity, "renew_ts": time.time()}, f)
        os.replace(tmp, self.lock_path)

    def try_acquire(self) -> bool:
        """Compare-and-swap on the lease, serialized by an flock mutex.

        The reference's resourcelock does CAS through the API server's
        resourceVersion; plain rename/O_EXCL dances cannot express
        'replace only if unchanged' (a holder resuming from a long stall
        could clobber a freshly stolen lease → split brain), so the
        read-check-write runs under an exclusive flock on a sidecar mutex
        file instead."""
        import fcntl

        if self._stop.is_set():
            # release()/fence() is clearing the lease: an in-flight
            # renew must not re-acquire it for the dying identity.
            self.is_leader = False
            return False
        with open(f"{self.lock_path}.mutex", "a+") as mutex:
            try:
                # Non-blocking: a peer frozen INSIDE the critical section
                # must not wedge every other contender forever (flock is
                # only released on process exit) — failing this attempt
                # and retrying preserves the lease-expiry liveness story.
                fcntl.flock(mutex, fcntl.LOCK_EX | fcntl.LOCK_NB)
            except OSError:
                self.is_leader = False
                return False
            try:
                lease = self._read_lease()
                now = time.time()
                can_take = (
                    lease is None
                    or lease["holder"] == self.identity
                    or now - lease["renew_ts"] > self.lease_duration
                )
                if can_take:
                    self._write_lease()
                self.is_leader = can_take
                return self.is_leader
            finally:
                fcntl.flock(mutex, fcntl.LOCK_UN)

    def run(self, on_started_leading, on_stopped_leading) -> None:
        """Block until leadership is acquired, then run the payload while
        renewing every retry_period (reference leaderelection.RunOrDie)."""
        while not self._stop.is_set() and not self.try_acquire():
            logger.info("leader election: lease held by another instance; retrying")
            self._stop.wait(self.retry_period)
        if self._stop.is_set():
            return

        lost = threading.Event()
        self._lost = lost

        def renew_loop():
            last_renew = time.time()
            while not self._stop.is_set() and not lost.is_set():
                if self.try_acquire():
                    last_renew = time.time()
                elif time.time() - last_renew > self.renew_deadline:
                    lost.set()
                    break
                self._stop.wait(self.retry_period)

        self._renew_thread = threading.Thread(
            target=renew_loop, daemon=True, name="leader-renew"
        )
        self._renew_thread.start()
        try:
            on_started_leading(lost)
        finally:
            if lost.is_set():
                self.is_leader = False
                on_stopped_leading()

    def release(self) -> None:
        self._stop.set()
        # Drain the renew loop BEFORE removing the lease file: a renew
        # whose read-check-write straddles the removal would re-create
        # the lease for a dying identity, pinning the cluster to it for
        # a full lease_duration (the same zombie-renew race the Kube
        # elector drains; fence() relies on this ordering too).
        if (
            self._renew_thread is not None
            and self._renew_thread is not threading.current_thread()
        ):
            self._renew_thread.join(timeout=10.0)
        lease = self._read_lease()
        if lease and lease["holder"] == self.identity:
            try:
                os.remove(self.lock_path)
            except OSError:
                pass


class KubeLeaseElector(LeaderElector):
    """Leader election over a coordination/v1 Lease in the API server —
    the reference's ConfigMap resourcelock analog (server.go:113-141),
    giving cross-HOST failover in real-cluster mode where the file lease
    only covers processes sharing a disk. Reuses LeaderElector's
    acquire/renew loop; only the CAS differs (API-server resourceVersion
    instead of an flock'd file)."""

    def __init__(
        self,
        cluster,
        namespace: str,
        identity: str,
        name: str = "tpu-batch",
        lease_duration: float = LEASE_DURATION,
        renew_deadline: float = RENEW_DEADLINE,
        retry_period: float = RETRY_PERIOD,
    ):
        self.cluster = cluster
        self.namespace = namespace
        self.name = name
        self.identity = identity
        self.lease_duration = lease_duration
        self.renew_deadline = renew_deadline
        self.retry_period = retry_period
        self._renew_thread: Optional[threading.Thread] = None
        self._stop = threading.Event()
        self.is_leader = False
        self._lost: Optional[threading.Event] = None
        self.fenced_reason: Optional[str] = None
        # True once this identity has EVER held the lease. release()
        # keys on this, not on the last attempt's is_leader: a transient
        # API failure (or lost CAS) right before shutdown flips
        # is_leader False while the API server still records us as
        # holder — skipping release then forces the successor to wait
        # out the full lease_duration (r2 advisor).
        self.held_at_least_once = False

    def try_acquire(self) -> bool:
        if self._stop.is_set():
            # release() is clearing the lease: an in-flight renew must
            # not re-acquire it for the dying identity.
            return False
        try:
            self.is_leader = self.cluster.try_acquire_lease(
                self.namespace, self.name, self.identity,
                self.lease_duration,
            )
            if self.is_leader:
                self.held_at_least_once = True
        except Exception:
            # Transient API failure: this attempt fails; the renew loop's
            # renew_deadline decides when failing attempts lose leadership.
            logger.exception("lease acquire attempt failed")
            self.is_leader = False
        return self.is_leader

    def release(self) -> None:
        self._stop.set()
        # Drain the renew loop BEFORE clearing the holder: a renew whose
        # API call straddles the release would otherwise re-write
        # holderIdentity after we cleared it, re-pinning the lease to a
        # dying process for the full lease_duration.
        if self._renew_thread is not None:
            self._renew_thread.join(timeout=10.0)
        if self.held_at_least_once:
            # release_lease clears the holder only if it is still this
            # identity, so releasing after a genuine takeover is a no-op.
            self.cluster.release_lease(
                self.namespace, self.name, self.identity
            )
            self.is_leader = False


def run(opt: ServerOption, cluster: Optional[ClusterAPI] = None,
        stop_event: Optional[threading.Event] = None) -> None:
    """reference app/server.go:63-141 Run."""
    register_options(opt)
    if cluster is None:
        if opt.master or opt.kubeconfig:
            # Real-cluster mode (reference server.go:56-61 buildConfig).
            from ..cluster.kube import KubeCluster, KubeConfig

            cluster = KubeCluster(
                KubeConfig.resolve(
                    kubeconfig=opt.kubeconfig, master=opt.master
                )
            )
        elif opt.cluster_state:
            cluster = load_cluster_state(
                opt.cluster_state, simulate_kubelet=opt.simulate_kubelet
            )
        else:
            cluster = InProcessCluster(simulate_kubelet=opt.simulate_kubelet)

    cache = new_scheduler_cache(
        cluster, opt.scheduler_name, opt.default_queue,
        enable_priority_class=opt.enable_priority_class,
    )
    sched = Scheduler(
        cache,
        scheduler_conf=opt.scheduler_conf or None,
        schedule_period=opt.schedule_period,
    )

    # Resolve the accelerator backend ONCE, bounded, before the first
    # cycle: a wedged tunnel plugin would otherwise hang the loop at its
    # first in-process jax call (bench/tests/graft entries already probe
    # this way; the daemon needs the same discipline). Wedged → CPU
    # devices + native solver routing, loudly.
    if any(a.name() == "allocate_tpu" for a in sched.actions):
        from ..utils.backend import ensure_live_backend

        devices = ensure_live_backend(timeout=opt.backend_probe_timeout)
        logger.info("jax backend ready: %d device(s)", devices)

    http_server, _ = start_metrics_server(opt.listen_address)
    # SIGUSR1 → flight-recorder dump. Installed HERE (cli.run is always
    # on the main thread) as well as in Scheduler.run, because signal
    # handlers cannot be installed from the worker thread an embedder
    # may drive the loop on.
    from ..obs import install_sigusr1

    install_sigusr1()
    stop = stop_event or threading.Event()

    def run_scheduler(lost_leadership: Optional[threading.Event] = None):
        if opt.once:
            cache.run(stop)
            cache.wait_for_cache_sync(stop)
            # Same recovery discipline as the loop: a --once run on a
            # cluster with surviving bind intents reconciles them
            # before its single cycle plans on top.
            try:
                sched.recover_from_journal()
            except Exception:
                logger.exception("--once journal recovery failed")
            sched.run_once()
            # Binds/evicts execute on the cache's async pool; barrier so
            # callers observe the fully-applied schedule after run().
            cache.wait_for_side_effects()
            return
        if lost_leadership is not None:
            # Chain: leadership loss stops the scheduling loop.
            def watch():
                lost_leadership.wait()
                stop.set()
            threading.Thread(target=watch, daemon=True).start()
        sched.run(stop)

    try:
        if not opt.enable_leader_election:
            run_scheduler()
            return

        opt.check_option_or_die()
        identity = f"{os.uname().nodename}-{os.getpid()}"
        # Journal records carry the elector identity, so a successor's
        # recovery can tell a dead predecessor's intents from its own;
        # the real-cluster journal Lease co-lives with the leader lock.
        cache.leader_identity = identity
        if getattr(cluster, "supports_lease_election", False):
            # Real-cluster journal Lease co-lives with the leader lock
            # (lock_object_namespace is a k8s namespace here; for the
            # file elector below it is a directory path).
            if hasattr(cluster, "journal_namespace"):
                cluster.journal_namespace = opt.lock_object_namespace
            # Real-cluster mode: the lock object lives in the API server
            # (coordination/v1 Lease — the reference's ConfigMap
            # resourcelock analog, server.go:113-141), so failover works
            # across hosts, not just processes on one machine.
            elector = KubeLeaseElector(
                cluster, opt.lock_object_namespace, identity=identity
            )
        else:
            elector = LeaderElector(
                opt.lock_object_namespace, identity=identity
            )
        # Zombie-leader fencing: a loop-watchdog trip (cycle hung past
        # its no-progress budget) stops lease renewal and releases it,
        # so a healthy instance can take over while the cache fence
        # keeps this process's side-effect threads from issuing binds.
        sched.fence_hooks.append(elector.fence)
        # Lease-TTL sanity: warn (and export at /debug/vars) when the
        # lease can expire under a healthy-but-slow leader before the
        # watchdog would fence it.
        sched.check_lease_ttl(elector.lease_duration)
        try:
            elector.run(
                on_started_leading=run_scheduler,
                on_stopped_leading=lambda: logger.error(
                    "lost leadership; stopping scheduling loop"
                ),
            )
        finally:
            elector.release()
    finally:
        stop.set()
        http_server.shutdown()
