"""Injectable scheduler clocks.

The ``Scheduler`` paces its loop through a clock object (``now()`` /
``wait(event, seconds)``) instead of calling ``time`` directly. The
production default lives in ``scheduler._WallClock`` (re-exported here
as ``RealClock`` — one implementation, not two copies that can drift);
the simulator injects ``VirtualClock``, whose ``wait`` advances the
timeline instantly instead of sleeping. ``real`` tells the scheduler
whether wall-clock-bounded side work (the think-time side-effect
drain) makes sense on this clock.
"""

from __future__ import annotations

from ..scheduler import _WallClock as RealClock

__all__ = ["RealClock", "VirtualClock"]


class VirtualClock:
    """Deterministic simulated timeline: waiting costs nothing and
    advances ``now()`` by exactly the requested amount."""

    real = False

    def __init__(self, start: float = 0.0):
        self._now = float(start)

    def now(self) -> float:
        return self._now

    def advance(self, seconds: float) -> float:
        if seconds > 0:
            self._now += seconds
        return self._now

    def wait(self, event, seconds: float) -> bool:
        self.advance(seconds)
        return event.is_set()
