"""Pass 4: unified doc↔code censuses — exact, both directions.

Hand-maintained doc tables rot the first time someone adds a name
without a row (or prunes one without deleting its row). Each census
here pins a table to the code-extracted truth and fails loudly either
way:

- **metrics**: ``metrics.REGISTRY`` vs the census tables in
  ``doc/design/metrics.md`` (the guard formerly run standalone by
  ``tests/unit/test_metrics_census.py``, which stays as the runtime
  twin — this pass is the fast-fail front door in ``make kbtlint``);
- **env vars**: every ``KBT_*`` string literal in the scheduler
  package (env accesses are the only reason such a literal exists) vs
  the marked table in ``doc/design/configuration.md``;
- **flight-record keys**: keys written into flight-recorder records
  (record dict literals + ``rec[...]`` writes + ``annotate(...)``
  literals + ``end_cycle(...)`` extras) vs the marked table in
  ``doc/design/observability.md``;
- **debug-vars keys**: top-level keys of the ``/debug/vars`` payload
  (``cli/server.py _debug_vars``) vs its marked table in
  ``doc/design/observability.md``.

Marked tables are delimited by ``<!-- kbtlint-census:NAME -->`` /
``<!-- /kbtlint-census:NAME -->`` comments; rows are ``| `token` |
...``. Names starting with ``_`` are internal and excluded on both
sides.
"""

from __future__ import annotations

import ast
import importlib.util
import os
import re
from typing import Dict, List, Optional, Set, Tuple

from .core import REPO, Finding, Project, call_name, register_pass

PASS_ID = "census"

_ROW_RE = re.compile(r"^\|\s*`([A-Za-z0-9_/]+)`\s*\|")
_KBT_RE = re.compile(r"^KBT_[A-Z0-9_]+$")

CONFIG_DOC = os.path.join("doc", "design", "configuration.md")
OBS_DOC = os.path.join("doc", "design", "observability.md")
METRICS_DOC = os.path.join("doc", "design", "metrics.md")


def _marked_rows(doc_path: str, name: str) -> Tuple[Optional[List[str]], int]:
    """Row tokens (in order, duplicates kept) of the census region(s)
    named ``name`` in ``doc_path`` — a doc may carry several marked
    regions under one name (metrics.md wraps each of its tables).
    (None, 0) when no marker exists."""
    path = os.path.join(REPO, doc_path)
    if not os.path.exists(path):
        return None, 0
    begin = f"<!-- kbtlint-census:{name} -->"
    end = f"<!-- /kbtlint-census:{name} -->"
    tokens: List[str] = []
    inside = False
    begin_line = 0
    with open(path) as f:
        for lineno, line in enumerate(f, start=1):
            stripped = line.strip()
            if stripped == begin:
                inside = True
                if begin_line == 0:
                    begin_line = lineno
                continue
            if stripped == end:
                inside = False
                continue
            if inside:
                m = _ROW_RE.match(stripped)
                if m:
                    tokens.append(m.group(1))
    if begin_line == 0:
        return None, 0
    return tokens, begin_line


def read_marked_table(doc_path: str, name: str) -> Tuple[Optional[Set[str]], int]:
    """Token set of the census table ``name`` in ``doc_path``, plus the
    first marker's line for finding attribution."""
    rows, line = _marked_rows(doc_path, name)
    return (None if rows is None else set(rows)), line


def compare_census(
    label: str,
    code_names: Set[str],
    doc_names: Optional[Set[str]],
    doc_rel: str,
    doc_line: int,
) -> List[Finding]:
    findings: List[Finding] = []
    if doc_names is None:
        findings.append(Finding(
            PASS_ID, doc_rel, 1,
            f"{label} census table missing: no "
            f"<!-- kbtlint-census:... --> marker found in {doc_rel}",
        ))
        return findings
    for name in sorted(code_names - doc_names):
        findings.append(Finding(
            PASS_ID, doc_rel, doc_line,
            f"{label} census: {name!r} exists in code but has no row "
            f"in {doc_rel}",
        ))
    for name in sorted(doc_names - code_names):
        findings.append(Finding(
            PASS_ID, doc_rel, doc_line,
            f"{label} census: {name!r} has a row in {doc_rel} but no "
            f"longer exists in code (stale row)",
        ))
    return findings


# -- metrics -----------------------------------------------------------------


def _load_registry_names() -> Set[str]:
    """Import kube_batch_tpu/metrics/metrics.py standalone (it is
    stdlib-only) — the same REGISTRY truth the runtime twin test uses,
    without paying a package import."""
    path = os.path.join(REPO, "kube_batch_tpu", "metrics", "metrics.py")
    spec = importlib.util.spec_from_file_location("_kbtlint_metrics", path)
    mod = importlib.util.module_from_spec(spec)
    spec.loader.exec_module(mod)
    return set(mod.REGISTRY.names())


def metrics_census() -> List[Finding]:
    # Marked regions only (metrics.md wraps each metric table): a
    # non-registry table elsewhere in the doc (bucket policy, env
    # cross-references) must not read as stale census rows.
    rows, line = _marked_rows(METRICS_DOC, "metrics")
    findings: List[Finding] = []
    if rows is None:
        return compare_census("metrics", _load_registry_names(), None,
                              METRICS_DOC, 0)
    for name in sorted({n for n in rows if rows.count(n) > 1}):
        findings.append(Finding(
            PASS_ID, METRICS_DOC, line,
            f"metrics census: duplicate row for {name!r}",
        ))
    findings.extend(compare_census(
        "metrics", _load_registry_names(), set(rows), METRICS_DOC, line
    ))
    return findings


# -- env vars ----------------------------------------------------------------


def _package_files(project: Project):
    """The scheduler package only: tools/ and bench.py carry KBT_*
    literals ABOUT the package (seeded self-test names, fixture
    snippets, env plumbing in drivers) that are not operator knobs."""
    for pf in project.files:
        rel = pf.rel.replace("\\", "/")
        if rel.startswith("kube_batch_tpu/"):
            yield pf


def collect_env_names(project: Project) -> Set[str]:
    names: Set[str] = set()
    for pf in _package_files(project):
        for node in ast.walk(pf.tree):
            if (
                isinstance(node, ast.Constant)
                and isinstance(node.value, str)
                and _KBT_RE.match(node.value)
            ):
                names.add(node.value)
    return names


# -- flight-record keys ------------------------------------------------------

_REC_NAMES = frozenset({"rec", "prev", "open_rec"})


def collect_flight_keys(project: Project) -> Set[str]:
    keys: Set[str] = set()
    recorder = None
    for pf in _package_files(project):
        if pf.rel.replace("\\", "/").endswith("obs/flightrecorder.py"):
            recorder = pf
        for node in ast.walk(pf.tree):
            if not isinstance(node, ast.Call):
                continue
            name = call_name(node)
            if name == "annotate" and node.args:
                first = node.args[0]
                if isinstance(first, ast.Constant) and isinstance(
                    first.value, str
                ):
                    keys.add(first.value)
            elif name == "end_cycle":
                for kw in node.keywords:
                    if kw.arg is not None and kw.arg != "ok":
                        keys.add(kw.arg)
    if recorder is not None:
        for node in ast.walk(recorder.tree):
            if isinstance(node, ast.Assign) and len(node.targets) == 1:
                target = node.targets[0]
                if (
                    isinstance(target, ast.Name)
                    and target.id in _REC_NAMES
                    and isinstance(node.value, ast.Dict)
                ):
                    for key in node.value.keys:
                        if isinstance(key, ast.Constant) and isinstance(
                            key.value, str
                        ):
                            keys.add(key.value)
                elif (
                    isinstance(target, ast.Subscript)
                    and isinstance(target.value, ast.Name)
                    and target.value.id in _REC_NAMES
                    and isinstance(target.slice, ast.Constant)
                    and isinstance(target.slice.value, str)
                ):
                    keys.add(target.slice.value)
    return {k for k in keys if not k.startswith("_")}


# -- /debug/vars keys --------------------------------------------------------


def collect_debug_vars_keys(project: Project) -> Set[str]:
    keys: Set[str] = set()
    for pf in project.files:
        if not pf.rel.replace("\\", "/").endswith("cli/server.py"):
            continue
        for node in ast.walk(pf.tree):
            if not isinstance(node, ast.FunctionDef):
                continue
            if node.name != "_debug_vars":
                continue
            for sub in ast.walk(node):
                if isinstance(sub, ast.Assign) and len(sub.targets) == 1:
                    target = sub.targets[0]
                    if (
                        isinstance(target, ast.Name)
                        and target.id == "out"
                        and isinstance(sub.value, ast.Dict)
                    ):
                        for key in sub.value.keys:
                            if isinstance(key, ast.Constant) and isinstance(
                                key.value, str
                            ):
                                keys.add(key.value)
                    elif (
                        isinstance(target, ast.Subscript)
                        and isinstance(target.value, ast.Name)
                        and target.value.id == "out"
                        and isinstance(target.slice, ast.Constant)
                        and isinstance(target.slice.value, str)
                    ):
                        keys.add(target.slice.value)
    return keys


@register_pass(PASS_ID)
def run(project: Project) -> List[Finding]:
    findings: List[Finding] = []
    findings.extend(metrics_census())

    env_doc, env_line = read_marked_table(CONFIG_DOC, "env-vars")
    findings.extend(compare_census(
        "KBT env-var", collect_env_names(project), env_doc,
        CONFIG_DOC, env_line,
    ))

    flight_doc, flight_line = read_marked_table(OBS_DOC, "flight-keys")
    findings.extend(compare_census(
        "flight-record key", collect_flight_keys(project), flight_doc,
        OBS_DOC, flight_line,
    ))

    debug_doc, debug_line = read_marked_table(OBS_DOC, "debug-vars")
    findings.extend(compare_census(
        "/debug/vars key", collect_debug_vars_keys(project), debug_doc,
        OBS_DOC, debug_line,
    ))

    findings.sort(key=lambda f: (f.file, f.line, f.message))
    return findings
