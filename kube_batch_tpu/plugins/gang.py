"""Gang plugin: all-or-nothing minMember semantics.

Mirrors reference plugins/gang/gang.go:
- JobValidFn: ValidTaskNum >= MinAvailable else NotEnoughTasks (:48-66).
- Preemptable/Reclaimable: a victim is only evictable if its job stays at or
  above minAvailable afterwards (:70-93).
- JobOrderFn: not-ready jobs first (:97-119).
- JobReady/JobPipelined from JobInfo.Ready/Pipelined (:121-128).
- OnSessionClose: Unschedulable PodGroup conditions + unschedulable metrics
  (:132-160).
"""

from __future__ import annotations

from .. import metrics
from ..api import (
    NOT_ENOUGH_PODS_REASON,
    NOT_ENOUGH_RESOURCES_REASON,
    POD_GROUP_CONDITION_UNSCHEDULABLE,
    JobInfo,
    PodGroupCondition,
    ValidateResult,
)
from ..framework import Plugin, register_plugin_builder


class GangPlugin(Plugin):
    def __init__(self, arguments=None):
        self.arguments = arguments or {}

    def name(self) -> str:
        return "gang"

    def on_session_open(self, ssn) -> None:
        def valid_job_fn(job) -> ValidateResult:
            if not isinstance(job, JobInfo):
                return ValidateResult(
                    passed=False, message=f"Failed to convert {job!r} to JobInfo"
                )
            vtn = job.valid_task_num()
            if vtn < job.min_available:
                return ValidateResult(
                    passed=False,
                    reason=NOT_ENOUGH_PODS_REASON,
                    message=(
                        f"Not enough valid tasks for gang-scheduling, "
                        f"valid: {vtn}, min: {job.min_available}"
                    ),
                )
            return None

        ssn.add_job_valid_fn(self.name(), valid_job_fn)

        def preemptable_fn(preemptor, preemptees):
            victims = []
            for preemptee in preemptees:
                job = ssn.jobs[preemptee.job]
                occupied = job.ready_task_num()
                preemptable = (
                    job.min_available <= occupied - 1 or job.min_available == 1
                )
                if preemptable:
                    victims.append(preemptee)
            return victims

        ssn.add_reclaimable_fn(self.name(), preemptable_fn)
        ssn.add_preemptable_fn(self.name(), preemptable_fn)

        def job_order_fn(l, r) -> int:
            l_ready, r_ready = l.ready(), r.ready()
            if l_ready and r_ready:
                return 0
            if l_ready:
                return 1
            if r_ready:
                return -1
            return 0

        ssn.add_job_order_fn(self.name(), job_order_fn)

        def batch_job_order_key(jobs):
            import numpy as np

            # Ascending key ≡ job_order_fn: not-ready gangs first. One
            # readiness evaluation per job instead of one per comparison
            # (job.ready() re-sums the status index on every call, so
            # the comparison sort paid it O(J log J) times per queue).
            return np.asarray(
                [1.0 if j.ready() else 0.0 for j in jobs], np.float64
            )

        ssn.add_batch_job_order_key_fn(self.name(), batch_job_order_key)
        ssn.add_job_ready_fn(self.name(), lambda job: job.ready())
        ssn.add_job_pipelined_fn(self.name(), lambda job: job.pipelined())

    def on_session_close(self, ssn) -> None:
        unschedulable_jobs = 0
        for job in ssn.jobs.values():
            if not job.ready():
                unready = job.min_available - job.ready_task_num()
                msg = (
                    f"{unready}/{len(job.tasks)} tasks in gang unschedulable: "
                    f"{job.fit_error()}"
                )
                unschedulable_jobs += 1
                metrics.update_unschedulable_task_count(job.name, int(unready))
                metrics.register_job_retries(job.name)
                cond = PodGroupCondition(
                    type=POD_GROUP_CONDITION_UNSCHEDULABLE,
                    status="True",
                    transition_id=ssn.uid,
                    reason=NOT_ENOUGH_RESOURCES_REASON,
                    message=msg,
                )
                try:
                    ssn.update_job_condition(job, cond)
                except KeyError:
                    pass
        metrics.update_unschedulable_job_count(unschedulable_jobs)


register_plugin_builder("gang", lambda args: GangPlugin(args))
