"""Predicates plugin: node feasibility checks.

Mirrors reference plugins/predicates/predicates.go (:113-265), which delegates
to the vendored k8s default-scheduler predicates. Implemented natively here
against the standalone object model, same check set and order:
- MaxTaskNum pod-count (:128)
- CheckNodeCondition (:133) — node Ready, not under unschedulable taint
- CheckNodeUnschedulable via spec (:147)
- PodMatchNodeSelector incl. required node affinity (:161)
- PodFitsHostPorts (:175)
- PodToleratesNodeTaints (:189)
- Memory/Disk/PID pressure, gated by plugin arguments
  predicate.{Memory,Disk,PID}PressureEnable (:75-110, :203-249)
- Inter-pod affinity/anti-affinity over session state (:252-262)

Each predicate raises PredicateError(reason) on rejection. The plugin also
registers a *batch* predicate (TPU-native extension) that evaluates the
static checks for a whole task batch as a [T, N] numpy mask — used by
ops.snapshot to build the device-side feasibility mask without a Python
per-(task, node) loop.
"""

from __future__ import annotations

from typing import List

import numpy as np

from ..api import NodeInfo, TaskInfo
from ..framework import Plugin, register_plugin_builder
from .util import (
    PLACED_STATUSES,
    PredicateError,
    SessionPodLister,
    match_affinity_term,
    match_label_selector,
    match_node_selector_terms,
)

# Per-pod memo attribute this plugin stamps onto (immutable) pod specs
# for tensorize speed: one tuple (template signature, has host ports,
# has inter-pod affinity) so a 50k-task cold burst pays ONE dict
# lookup + write per pod, not two. Anything that needs to re-cold the
# cache (the bench's burst simulation) must go through
# clear_pod_caches so the attr list lives in exactly one place.
POD_CACHE_ATTRS = ("_pred_cache",)


def clear_pod_caches(pods) -> None:
    """Drop the per-pod memos: this plugin's (POD_CACHE_ATTRS) plus the
    pod_key memo (api.helpers), so a re-cold simulation pays every
    first-touch cost a genuinely fresh pod would."""
    from ..api.helpers import POD_KEY_CACHE_ATTR

    attrs = POD_CACHE_ATTRS + (POD_KEY_CACHE_ATTR,)
    for pod in pods:
        for attr in attrs:
            if hasattr(pod, attr):
                delattr(pod, attr)


# Argument keys (reference predicates.go:75-95).
MEMORY_PRESSURE_ENABLE = "predicate.MemoryPressureEnable"
DISK_PRESSURE_ENABLE = "predicate.DiskPressureEnable"
PID_PRESSURE_ENABLE = "predicate.PIDPressureEnable"


def _node_condition(node: NodeInfo, cond_type: str) -> str:
    if node.node is None:
        return "Unknown"
    for c in node.node.status.conditions:
        if c.type == cond_type:
            return c.status
    return ""


def check_node_condition(task: TaskInfo, node: NodeInfo) -> None:
    """Node must be Ready and not OutOfDisk (k8s CheckNodeCondition)."""
    ready = _node_condition(node, "Ready")
    if ready not in ("", "True"):
        raise PredicateError("NodeNotReady", f"node {node.name} is not ready")
    if _node_condition(node, "OutOfDisk") == "True":
        raise PredicateError("NodeOutOfDisk", f"node {node.name} is out of disk")


def check_node_unschedulable(task: TaskInfo, node: NodeInfo) -> None:
    if node.node is not None and node.node.spec.unschedulable:
        raise PredicateError(
            "NodeUnschedulable", f"node {node.name} is unschedulable"
        )


def check_max_task_num(task: TaskInfo, node: NodeInfo) -> None:
    """reference predicates.go:128-131"""
    if len(node.tasks) >= node.allocatable.max_task_num > 0:
        raise PredicateError(
            "NodePodNumberExceeded",
            f"node {node.name} has {len(node.tasks)} tasks, "
            f"max {node.allocatable.max_task_num}",
        )


def pod_match_node_selector(task: TaskInfo, node: NodeInfo) -> None:
    """nodeSelector + required node affinity (k8s PodMatchNodeSelector)."""
    labels = node.node.metadata.labels if node.node else {}
    if task.pod.spec.node_selector and not match_label_selector(
        task.pod.spec.node_selector, labels
    ):
        raise PredicateError(
            "MatchNodeSelector", f"node {node.name} does not match node selector"
        )
    affinity = task.pod.spec.affinity
    if affinity and affinity.node_required is not None:
        if not match_node_selector_terms(affinity.node_required, labels):
            raise PredicateError(
                "MatchNodeSelector",
                f"node {node.name} does not match required node affinity",
            )


def pod_fits_host_ports(task: TaskInfo, node: NodeInfo) -> None:
    wanted = set()
    for c in task.pod.spec.containers:
        wanted.update(c.ports)
    if not wanted:
        return
    for other in node.tasks.values():
        for c in other.pod.spec.containers:
            if wanted.intersection(c.ports):
                raise PredicateError(
                    "PodFitsHostPorts", f"host port conflict on {node.name}"
                )


def pod_tolerates_node_taints(task: TaskInfo, node: NodeInfo) -> None:
    if node.node is None:
        return
    for taint in node.node.spec.taints:
        if taint.effect not in ("NoSchedule", "NoExecute"):
            continue  # PreferNoSchedule is a soft constraint
        if not any(t.tolerates(taint) for t in task.pod.spec.tolerations):
            raise PredicateError(
                "PodToleratesNodeTaints",
                f"taint {taint.key}={taint.value}:{taint.effect} not tolerated",
            )


def _check_pressure(node: NodeInfo, cond_type: str, reason: str) -> None:
    if _node_condition(node, cond_type) == "True":
        raise PredicateError(reason, f"node under {cond_type}")


class _PredNodeCache:
    """Cross-cycle node-column + template-group-row cache for the batch
    predicate (stored on the scheduler cache as ``_pred_batch_cache``).

    Same fingerprint contract as the tensorize cache
    (solver/snapshot._TensorizeCache): the COW snapshot pool hands
    consecutive sessions identical NodeInfo clone objects while nothing
    changed, and every mutator bumps ``_ver``, so ``(identity, _ver)``
    exactly identifies a node whose verdict columns are still valid.
    Holding the node references pins their ids. ``sig_rows`` maps a pod
    template signature to ``(rep_pod, has_selaff, row)`` — the [N] group
    row is patched column-wise for dirty nodes and reused whole for the
    rest."""

    __slots__ = (
        "flags", "node_objs", "node_ids", "node_vers", "node_ok",
        "has_taints", "static_ok", "sig_rows",
    )
    # Retention bound for template rows whose signature did not appear
    # in the current batch (kept warm so alternating bursts reuse them).
    MAX_RETAINED_SIGS = 128

    def __init__(self):
        self.flags = None
        self.node_objs = None
        self.node_ids = None
        self.node_vers = None
        self.node_ok = None
        self.has_taints = None
        # Watch-object-only half of node_ok (conditions, cordon,
        # pressure): invariant under the scheduler's own placements, so
        # narrow-dirty nodes recompute only the live pod-count cap.
        self.static_ok = None
        self.sig_rows = {}


class _SigRep:
    """Minimal task stand-in for re-evaluating a cached signature row
    (the predicate helpers only read ``task.pod``)."""

    __slots__ = ("pod",)

    def __init__(self, pod):
        self.pod = pod


class PredicatesPlugin(Plugin):
    def __init__(self, arguments=None):
        self.arguments = arguments or {}

    def name(self) -> str:
        return "predicates"

    def _pressure_flags(self):
        getb = getattr(self.arguments, "get_bool", None)
        if getb is None:
            return False, False, False
        return (
            bool(getb(MEMORY_PRESSURE_ENABLE, False)),
            bool(getb(DISK_PRESSURE_ENABLE, False)),
            bool(getb(PID_PRESSURE_ENABLE, False)),
        )

    def on_session_open(self, ssn) -> None:
        mem_enable, disk_enable, pid_enable = self._pressure_flags()
        lister = SessionPodLister(ssn)

        def check_pod_affinity(task: TaskInfo, node: NodeInfo) -> None:
            """Simplified inter-pod (anti-)affinity with node-level topology
            (reference predicates.go:252-262 via vendored k8s checker)."""
            affinity = task.pod.spec.affinity
            if affinity is None:
                return
            on_node = lister.pods_on_node(node.name)
            for term in affinity.pod_affinity or []:
                if not any(
                    match_affinity_term(term, t.pod.metadata.labels)
                    for t in on_node
                ):
                    # k8s bootstrap rule (vendored predicates
                    # satisfiesPodsAffinityAntiAffinity): a required term with
                    # NO matching pod anywhere is satisfied if the incoming
                    # pod itself matches the selector — the first pod of a
                    # self-affine group must be schedulable somewhere.
                    exists_anywhere = any(
                        match_affinity_term(term, t.pod.metadata.labels)
                        for t in lister.tasks()
                        if t.uid != task.uid and t.status in PLACED_STATUSES
                    )
                    if exists_anywhere or not match_affinity_term(
                        term, task.pod.metadata.labels
                    ):
                        raise PredicateError(
                            "MatchInterPodAffinity",
                            f"pod affinity not satisfied on {node.name}",
                        )
            for term in affinity.pod_anti_affinity or []:
                if any(
                    match_affinity_term(term, t.pod.metadata.labels)
                    for t in on_node
                    if t.uid != task.uid
                ):
                    raise PredicateError(
                        "MatchInterPodAntiAffinity",
                        f"pod anti-affinity violated on {node.name}",
                    )

        def predicate_fn(task: TaskInfo, node: NodeInfo) -> None:
            """reference predicates.go:124-264, same check order."""
            check_max_task_num(task, node)
            check_node_condition(task, node)
            check_node_unschedulable(task, node)
            pod_match_node_selector(task, node)
            pod_fits_host_ports(task, node)
            pod_tolerates_node_taints(task, node)
            if mem_enable:
                _check_pressure(node, "MemoryPressure", "NodeUnderMemoryPressure")
            if disk_enable:
                _check_pressure(node, "DiskPressure", "NodeUnderDiskPressure")
            if pid_enable:
                _check_pressure(node, "PIDPressure", "NodeUnderPIDPressure")
            check_pod_affinity(task, node)

        ssn.add_predicate_fn(self.name(), predicate_fn)

        def batch_predicate_fn(tasks: List[TaskInfo], nodes: List[NodeInfo]):
            """Factorized feasibility (solver/masks.BatchMask).

            Node-level checks (conditions, unschedulable, pressure,
            pod-count) produce one [N] column mask. Tolerations, node
            selectors, and required node affinity are functions of the pod
            TEMPLATE, not the pod — tasks are grouped by their
            (tolerations, selector, affinity) signature and each of the G
            distinct signatures is evaluated against all nodes once:
            O(N + G·N) host work instead of O(T·N). Host ports and
            inter-pod (anti-)affinity depend on per-node session state and
            get private per-task rows (sparse: only tasks that carry
            them).

            This factorization is ALSO what the top-K candidate
            selection pass consumes (solver/topk.py): combine_masks
            folds these parts into CombinedMask, whose ``rows_for``
            emits per-class candidate-column masks — one row per
            distinct (group, req/fit, private-row) class, not per task.
            A custom plugin returning a dense [T, N] mask still works
            (combine_masks dedups identical rows into groups), but any
            per-task row variance it introduces multiplies the class
            count and can push the selection pass over its budget
            (dense fallback, reason "class-budget") — prefer BatchMask's
            group/pair form."""
            from ..solver.masks import BatchMask

            T, N = len(tasks), len(nodes)

            # Node column: the static verdict (conditions, cordon,
            # pressure gates, has-taints) reads only the immutable
            # watch object, so it is memoized on node.node keyed by the
            # pressure-flag combo — a watch update replaces the object
            # and invalidates naturally, exactly like the pod spec memo
            # below. Only the pod-count cap stays live per cycle.
            flags = (mem_enable, disk_enable, pid_enable)

            def node_verdict(node):
                """(schedulable, has_taints, static_ok) for one node —
                exactly the pre-incremental per-node loop body, with the
                watch-object-only half exposed for the narrow-churn
                fast path."""
                knode = node.node
                if knode is None:
                    # No backing object: evaluate directly (the checks
                    # define the Unknown-condition semantics).
                    try:
                        check_node_condition(None, node)
                        check_node_unschedulable(None, node)
                    except PredicateError:
                        return False, False, False
                    has_taints = False
                else:
                    # Unlike pod specs, node specs/conditions are
                    # MUTABLE: the memo key carries id(owner) — a
                    # copied object (copy.deepcopy in tests/tools)
                    # inherits the attr but its own id never matches —
                    # AND the NodeInfo's watch-object generation
                    # (bumped by set_node), which catches an in-place
                    # mutation re-delivered as the SAME reference
                    # (InProcessCluster.update does exactly that).
                    gen = node._node_obj_ver
                    cached = knode.__dict__.get("_node_pred")
                    if (
                        cached is None
                        or cached[0] != (flags, id(knode), gen)
                    ):
                        ok = True
                        try:
                            check_node_condition(None, node)
                            check_node_unschedulable(None, node)
                            if mem_enable:
                                _check_pressure(node, "MemoryPressure", "x")
                            if disk_enable:
                                _check_pressure(node, "DiskPressure", "x")
                            if pid_enable:
                                _check_pressure(node, "PIDPressure", "x")
                        except PredicateError:
                            ok = False
                        cached = knode._node_pred = (
                            (flags, id(knode), gen),
                            ok,
                            bool(knode.spec.taints),
                        )
                    has_taints = cached[2]
                    if not cached[1]:
                        return False, has_taints, False
                if 0 < node.allocatable.max_task_num <= len(node.tasks):
                    return False, has_taints, True
                return True, has_taints, True

            # Cross-cycle columns (see _PredNodeCache): dirty nodes are
            # the fingerprint misses; only their verdicts re-run. A
            # flags/node-set change rebuilds everything. This pass ran
            # over EVERY node EVERY cycle before — it was most of the
            # 1%-delta tensorize floor at 5k nodes.
            pc = None
            cache_host = getattr(ssn, "cache", None)
            if cache_host is not None:
                pc = getattr(cache_host, "_pred_batch_cache", None)
                if pc is None:
                    pc = _PredNodeCache()
                    try:
                        cache_host._pred_batch_cache = pc
                    except Exception:
                        pc = None
            # Shared per-tensorize node scan (solver/snapshot): the
            # (identity, _ver) arrays are computed once per cycle and
            # reused here when the caller passed its exact node list.
            scan = getattr(ssn, "_kbt_node_scan", None)
            if scan is not None and scan.nodes is nodes:
                cur_ids, cur_vers = scan.ids, scan.vers
            else:
                cur_ids = np.fromiter(map(id, nodes), np.int64, count=N)
                cur_vers = np.fromiter(
                    (n._ver for n in nodes), np.int64, count=N
                )
            if (
                pc is None
                or pc.node_objs is None
                or pc.node_ids is None
                or pc.flags != flags
                or pc.static_ok is None
                or len(pc.node_objs) != N
            ):
                node_ok = np.empty(N, dtype=bool)
                has_taints_col = np.empty(N, dtype=bool)
                static_ok_col = np.empty(N, dtype=bool)
                dirty = list(range(N))
                recheck = dirty
                prev_sig_rows = {}
            else:
                node_ok = pc.node_ok
                has_taints_col = pc.has_taints
                static_ok_col = pc.static_ok
                dirty = np.nonzero(
                    (cur_ids != pc.node_ids)
                    | (cur_vers != pc.node_vers)
                )[0].tolist()
                prev_sig_rows = pc.sig_rows
                # NARROW split: rows whose only churn was the
                # scheduler's own placements keep their watch-object
                # verdict and taint/selector columns — only the live
                # pod-count cap can move. Their sig-row columns need a
                # re-verdict ONLY when that cap flipped node_ok.
                narrow = getattr(ssn, "dirty_nodes_narrow", None)
                if dirty and narrow:
                    recheck = []
                    for j in dirty:
                        n = nodes[j]
                        if n.name in narrow:
                            ok = bool(static_ok_col[j]) and not (
                                0 < n.allocatable.max_task_num
                                <= len(n.tasks)
                            )
                            if ok != node_ok[j]:
                                # Pod-count cap flipped the verdict:
                                # fall through to the full re-verdict
                                # so the sig-row columns re-derive too.
                                recheck.append(j)
                        else:
                            recheck.append(j)
                else:
                    recheck = dirty
            for j in recheck:
                (
                    node_ok[j], has_taints_col[j], static_ok_col[j],
                ) = node_verdict(nodes[j])
            if pc is not None and (dirty or pc.node_objs is None):
                pc.flags = flags
                pc.node_objs = list(nodes)
                pc.node_ids = cur_ids
                pc.node_vers = cur_vers
                pc.node_ok = node_ok
                pc.has_taints = has_taints_col
                pc.static_ok = static_ok_col
            tainted = np.nonzero(node_ok & has_taints_col)[0].tolist()

            def _terms_sig(terms):
                # node_required is a list of terms (each a list of
                # expression dicts), or a flat expression list treated as
                # one term — mirror match_node_selector_terms.
                if terms and isinstance(terms[0], dict):
                    terms = [terms]
                return tuple(
                    tuple(
                        (
                            e.get("key"),
                            e.get("operator"),
                            tuple(e.get("values") or ()),
                        )
                        for e in term
                    )
                    for term in terms
                )

            # ONE pass over the task list: template-signature grouping
            # AND the private-row (host ports / inter-pod affinity)
            # verdicts together. Pod specs are immutable after creation
            # (k8s semantics), so everything derived from the spec is
            # cached on the pod object in one tuple — tasks are cloned
            # every snapshot but share the pod, making the derivation a
            # once-per-pod cost and this loop two dict ops per task
            # (measured: the split loops + separate caches were ~40% of
            # first-cycle tensorize at 50k tasks).
            sig_to_group: dict = {}
            task_group = np.empty(T, dtype=np.int32)
            reps: List[TaskInfo] = []
            sig_list: List[tuple] = []  # signature per group, ∥ reps
            private: List[tuple] = []  # (i, task, has_ports, has_pod_aff)
            sig_get = sig_to_group.get
            for i, task in enumerate(tasks):
                pod = task.pod
                cached = pod.__dict__.get("_pred_cache")
                if cached is None:
                    spec = pod.spec
                    # Plain pods (no tolerations/selector/affinity) are
                    # the bulk of a big snapshot; skip tuple building
                    # for their empty fields.
                    tol = spec.tolerations
                    tol_sig = tuple(
                        (t.key, t.operator, t.value, t.effect)
                        for t in tol
                    ) if tol else ()
                    sel = spec.node_selector
                    sel_sig = tuple(sorted(sel.items())) if sel else ()
                    aff = spec.affinity
                    req_aff = (
                        _terms_sig(aff.node_required)
                        if aff is not None and aff.node_required
                        else None
                    )
                    has_ports = False
                    for c in spec.containers:  # plain loop: a genexpr
                        if c.ports:            # frame per pod was ~9%
                            has_ports = True   # of a 50k cold tensorize
                            break
                    cached = pod._pred_cache = (
                        (tol_sig, sel_sig, req_aff),
                        has_ports,
                        aff is not None and bool(
                            aff.pod_affinity or aff.pod_anti_affinity
                        ),
                    )
                sig, has_ports, has_pod_aff = cached
                g = sig_get(sig)
                if g is None:
                    g = sig_to_group[sig] = len(reps)
                    reps.append(task)
                    sig_list.append(sig)
                task_group[i] = g
                if has_ports or has_pod_aff:
                    private.append((i, task, has_ports, has_pod_aff))

            def build_sig_row(rep, has_selaff):
                """Full [N] group row — the pre-incremental loops."""
                row = np.ones(N, dtype=bool)
                for j in tainted:
                    try:
                        pod_tolerates_node_taints(rep, nodes[j])
                    except PredicateError:
                        row[j] = False
                if has_selaff:
                    for j in range(N):
                        if not (node_ok[j] and row[j]):
                            continue
                        try:
                            pod_match_node_selector(rep, nodes[j])
                        except PredicateError:
                            row[j] = False
                return row

            def patch_sig_row(row, rep, has_selaff):
                """Re-verdict only the re-checked columns of a cached
                row (narrow-churn columns with an unchanged verdict are
                already exact). Column-for-column identical to
                build_sig_row: a not-ok node's column resets to True
                (never evaluated), taints then selector in order for
                the rest."""
                for j in recheck:
                    row[j] = True
                    if not node_ok[j]:
                        continue
                    if has_taints_col[j]:
                        try:
                            pod_tolerates_node_taints(rep, nodes[j])
                        except PredicateError:
                            row[j] = False
                            continue
                    if has_selaff:
                        try:
                            pod_match_node_selector(rep, nodes[j])
                        except PredicateError:
                            row[j] = False
                return row

            # Template-group rows, kept alive across cycles per
            # signature: a signature seen before costs O(dirty nodes);
            # only new signatures pay the O(N) build. Rows retained for
            # signatures absent from THIS batch (bounded) are patched
            # too, so they stay valid for the next burst.
            new_sig_rows: dict = {}
            group_rows = np.empty((len(reps), N), dtype=bool)
            for g, rep in enumerate(reps):
                spec = rep.pod.spec
                aff = spec.affinity
                has_selaff = bool(spec.node_selector) or (
                    aff is not None and bool(aff.node_required)
                )
                ent = prev_sig_rows.get(sig_list[g])
                if ent is None:
                    row = build_sig_row(rep, has_selaff)
                else:
                    row = patch_sig_row(ent[2], rep, has_selaff)
                new_sig_rows[sig_list[g]] = (rep.pod, has_selaff, row)
                group_rows[g] = row
            if pc is not None:
                for sig, ent in prev_sig_rows.items():
                    if sig in new_sig_rows:
                        continue
                    if len(new_sig_rows) >= _PredNodeCache.MAX_RETAINED_SIGS:
                        break
                    rep_pod, has_selaff, row = ent
                    new_sig_rows[sig] = (
                        rep_pod,
                        has_selaff,
                        patch_sig_row(row, _SigRep(rep_pod), has_selaff),
                    )
                pc.sig_rows = new_sig_rows

            # Private rows: host ports and inter-pod (anti-)affinity —
            # only for the (rare) tasks collected above.
            rows = {}
            for i, task, has_ports, has_pod_aff in private:
                row = np.ones(N, dtype=bool)
                for j, node in enumerate(nodes):
                    if not (node_ok[j] and group_rows[task_group[i], j]):
                        row[j] = False
                        continue
                    try:
                        if has_ports:
                            pod_fits_host_ports(task, node)
                        if has_pod_aff:
                            check_pod_affinity(task, node)
                    except PredicateError:
                        row[j] = False
                rows[i] = row

            return BatchMask(
                # Copy: the cache patches its column in place next cycle
                # and callers may hold the mask across cycles.
                node_ok=node_ok.copy(),
                task_group=task_group,
                group_rows=group_rows,
                rows=rows,
            )

        ssn.add_batch_predicate_fn(self.name(), batch_predicate_fn)


register_plugin_builder("predicates", lambda args: PredicatesPlugin(args))
