"""kbtlint self-test fixture: unstamped ledger mutation (known-bad).

``delete_pdb_like`` mutates a job's scheduling spec with no dirty
stamp reachable — the PR 8 warm-path staleness class.
"""


class MiniCache:
    def _stamp_dirty(self, job_key=None, node_name=None):
        if job_key:
            self._dirty_jobs.add(job_key)

    def delete_pdb_like(self, job):
        job.unset_pdb()
