"""Cluster-truth anti-entropy (cache/antientropy.py): divergence
classification per kind, budget bounding, in-flight exemption, repair
through the dirty ledger, and warm-solve parity across a repair."""

import os

from kube_batch_tpu.actions.allocate_tpu import last_stats
from kube_batch_tpu.api import PodPhase, TaskStatus, build_resource_list
from kube_batch_tpu.cache import SchedulerCache
from kube_batch_tpu.cluster import InProcessCluster
from kube_batch_tpu.framework import close_session, get_action, open_session
from kube_batch_tpu.utils.test_utils import (
    FakeBinder,
    FakeEvictor,
    FakeStatusUpdater,
    FakeVolumeBinder,
    build_node,
    build_pod,
    build_pod_group,
    build_queue,
)

from tests.actions.test_actions import DEFAULT_TIERS_ARGS, make_tiers


def req(cpu="1000m", mem="1Gi"):
    return dict(build_resource_list(cpu=cpu, memory=mem))


def make_cluster_cache(nodes=3):
    cluster = InProcessCluster(simulate_kubelet=True)
    cache = SchedulerCache(
        cluster=cluster,
        scheduler_name="tpu-batch",
        binder=FakeBinder(),
        evictor=FakeEvictor(),
        status_updater=FakeStatusUpdater(),
        volume_binder=FakeVolumeBinder(),
    )
    for j in range(nodes):
        cluster.create_node(build_node(
            f"n{j}", build_resource_list(cpu="8", memory="16Gi", pods=110)
        ))
    cluster.create_queue(build_queue("default"))
    cache.start_ingest()
    return cluster, cache


def make_pod(name, node="", phase=PodPhase.PENDING, group="g1"):
    pod = build_pod("ns", name, node, phase, req(), group_name=group)
    pod.spec.scheduler_name = "tpu-batch"
    return pod


def silent(cluster, cache, fn):
    """Apply a cluster mutation WITHOUT watch delivery — the divergence
    injector."""
    cluster.remove_watch(cache._on_watch_event)
    try:
        fn()
    finally:
        cluster.add_watch(cache._on_watch_event)


class TestClassification:
    def test_missed_pod(self):
        cluster, cache = make_cluster_cache()
        silent(cluster, cache, lambda: cluster.create_pod(make_pod("p1")))
        rep = cache.antientropy.sweep()
        assert rep["detected"] == {"missed-pod": 1}
        assert rep["repaired"] == {"missed-pod": 1}
        assert sum(len(j.tasks) for j in cache.jobs.values()) == 1
        # Repair stamped the dirty ledger (warm/tensorize coherence).
        assert "ns/g1" in cache._dirty_jobs
        cache.shutdown()

    def test_missed_bind(self):
        cluster, cache = make_cluster_cache()
        pod = make_pod("p1")
        cluster.create_pod(pod)
        silent(cluster, cache, lambda: cluster.bind_pod(pod, "n0"))
        rep = cache.antientropy.sweep()
        assert rep["detected"] == {"missed-bind": 1}
        task = next(
            t for j in cache.jobs.values() for t in j.tasks.values()
        )
        assert task.node_name == "n0"
        assert task.uid in cache.nodes["n0"].tasks
        cache.shutdown()

    def test_phantom_task(self):
        cluster, cache = make_cluster_cache()
        pod = make_pod("p1")
        cluster.create_pod(pod)
        silent(cluster, cache, lambda: cluster.delete_pod(pod))
        rep = cache.antientropy.sweep()
        assert rep["detected"] == {"phantom-task": 1}
        assert sum(len(j.tasks) for j in cache.jobs.values()) == 0
        cache.shutdown()

    def test_vanished_and_missed_node(self):
        cluster, cache = make_cluster_cache()
        node = next(
            n for n in cluster.list_objects("Node") if n.name == "n0"
        )
        silent(cluster, cache, lambda: cluster.delete("Node", node))
        new = build_node(
            "n9", build_resource_list(cpu="8", memory="16Gi", pods=110)
        )
        silent(cluster, cache, lambda: cluster.create_node(new))
        rep = cache.antientropy.sweep()
        assert rep["detected"] == {
            "vanished-node": 1, "missed-node": 1
        }
        assert "n0" not in cache.nodes and "n9" in cache.nodes
        cache.shutdown()

    def test_stale_node_capacity(self):
        cluster, cache = make_cluster_cache()
        node = next(
            n for n in cluster.list_objects("Node") if n.name == "n0"
        )

        def shrink():
            node.status.allocatable = build_resource_list(
                cpu="2", memory="4Gi", pods=110
            )
            cluster.update("Node", node)

        silent(cluster, cache, shrink)
        rep = cache.antientropy.sweep()
        assert rep["detected"] == {"stale-node": 1}
        assert cache.nodes["n0"].allocatable.milli_cpu == 2000.0
        cache.shutdown()

    def test_inflight_binding_task_exempt(self):
        """A BINDING task (side effect on the wire) must never be
        judged against truth mid-flight."""
        cluster, cache = make_cluster_cache()
        pod = make_pod("p1")
        cluster.create_pod(pod)
        job = next(iter(cache.jobs.values()))
        task = next(iter(job.tasks.values()))
        job.update_task_status(task, TaskStatus.BINDING)
        task.node_name = "n0"
        rep = cache.antientropy.sweep()
        assert rep["detected"] == {}
        assert rep["exempt_inflight"] == 1
        cache.shutdown()

    def test_orphaned_binding_task_repaired(self):
        """A BINDING task whose pod is GONE from truth (its bind
        confirm AND delete were both lost) is an orphan, not
        in-flight — the exemption must not shield it forever."""
        cluster, cache = make_cluster_cache()
        pod = make_pod("p1")
        cluster.create_pod(pod)
        job = next(iter(cache.jobs.values()))
        task = next(iter(job.tasks.values()))
        job.update_task_status(task, TaskStatus.BINDING)
        task.node_name = "n0"
        silent(cluster, cache, lambda: cluster.delete_pod(pod))
        rep = cache.antientropy.sweep()
        assert rep["detected"] == {"phantom-task": 1}
        assert rep["repaired"] == {"phantom-task": 1}
        assert sum(len(j.tasks) for j in cache.jobs.values()) == 0
        cache.shutdown()

    def test_terminated_orphan_repaired_but_live_terminated_skipped(self):
        """Terminated tasks are outside the fold: a SUCCEEDED pod still
        in the cluster is cleanup's business (no oscillation with the
        job-cleanup queue), but a mirror-terminated task whose pod is
        gone is a phantom."""
        cluster, cache = make_cluster_cache()
        pod = make_pod("p1")
        cluster.create_pod(pod)
        pod.status.phase = PodPhase.SUCCEEDED
        cluster.update("Pod", pod)
        rep = cache.antientropy.sweep()
        assert rep["detected"] == {}
        silent(cluster, cache, lambda: cluster.delete_pod(pod))
        rep = cache.antientropy.sweep()
        assert rep["detected"] == {"phantom-task": 1}
        assert sum(len(j.tasks) for j in cache.jobs.values()) == 0
        cache.shutdown()

    def test_budget_defers_remainder(self):
        cluster, cache = make_cluster_cache()

        def create_many():
            for i in range(6):
                cluster.create_pod(make_pod(f"p{i}"))

        silent(cluster, cache, create_many)
        rep = cache.antientropy.sweep(budget=2)
        assert sum(rep["repaired"].values()) == 2
        assert rep["deferred"] == 4
        rep2 = cache.antientropy.sweep(budget=None)
        assert sum(rep2["repaired"].values()) == 4
        rep3 = cache.antientropy.sweep()
        assert rep3["detected"] == {}
        cache.shutdown()

    def test_consistent_sweep_is_clean_and_counts(self):
        cluster, cache = make_cluster_cache()
        cluster.create_pod(make_pod("p1"))
        rep = cache.antientropy.sweep()
        assert rep["detected"] == {} and rep["buckets_dirty"] == 0
        state = cache.integrity_state()
        assert state["sweeps"] == 1
        assert state["divergence_detected"] == {}
        cache.shutdown()

    def test_sweep_cadence(self, monkeypatch):
        monkeypatch.setenv("KBT_ANTIENTROPY_EVERY", "3")
        cluster, cache = make_cluster_cache()
        ran = [
            cache.run_antientropy_if_due() is not None
            for _ in range(7)
        ]
        assert ran == [True, False, False, True, False, False, True]
        monkeypatch.setenv("KBT_ANTIENTROPY", "0")
        cache._antientropy = None
        assert cache.run_antientropy_if_due() is None
        cache.shutdown()


class TestWarmParityAcrossRepair:
    """Satellite: an anti-entropy repair must land in the dirty ledger
    so the warm-start plan voids its carried state — the post-repair
    solve is pinned bit-equal to a cold (KBT_WARM=0) twin run."""

    def _run(self, warm: bool):
        prev = os.environ.get("KBT_WARM")
        if warm:
            os.environ.pop("KBT_WARM", None)
        else:
            os.environ["KBT_WARM"] = "0"
        try:
            cluster, cache = make_cluster_cache(nodes=4)
            action, _ = get_action("allocate_tpu")
            tiers = make_tiers(*DEFAULT_TIERS_ARGS)

            def cycle():
                ssn = open_session(cache, tiers)
                action.execute(ssn)
                outcome = last_stats.get("warm_outcome")
                close_session(ssn)
                assert cache.wait_for_side_effects(timeout=30.0)
                assert cache.wait_for_bookkeeping(timeout=30.0)
                cache.drain_resync_queue()
                cache.drain_cleanup_queue()
                return outcome

            # Cycle 1: a wave places; cycle 2: warm steady state.
            cluster.create_pod_group(build_pod_group(
                "g1", namespace="ns", min_member=1, queue="default"
            ))
            for i in range(4):
                cluster.create_pod(make_pod(f"a{i}"))
            cycle()
            outcome2 = cycle()
            # Divergence behind the cache's back + repair by sweep.
            silent(
                cluster, cache,
                lambda: cluster.create_pod(make_pod("late1")),
            )
            rep = cache.antientropy.sweep()
            assert rep["repaired"] == {"missed-pod": 1}
            # Post-repair cycle must place the repaired pod.
            outcome3 = cycle()
            state = sorted(
                (t.name, t.node_name, t.status.name)
                for j in cache.jobs.values()
                for t in j.tasks.values()
            )
            idle = {
                name: (ni.idle.milli_cpu, ni.used.milli_cpu)
                for name, ni in sorted(cache.nodes.items())
            }
            cache.shutdown()
            return state, idle, (outcome2, outcome3)
        finally:
            if prev is None:
                os.environ.pop("KBT_WARM", None)
            else:
                os.environ["KBT_WARM"] = prev

    def test_bit_equal_vs_cold(self):
        warm_state, warm_idle, outcomes = self._run(warm=True)
        cold_state, cold_idle, _ = self._run(warm=False)
        assert warm_state == cold_state
        assert warm_idle == cold_idle
        # The warm run actually exercised the warm machinery, and the
        # post-repair cycle did NOT sail through as a noop reuse of
        # carried verdicts — the repair dirtied the world.
        assert outcomes[0] in ("noop", "solve")
        assert outcomes[1] != "noop"
        # Every repaired pod ended placed.
        assert any(name == "late1" and node for name, node, _s
                   in warm_state)
