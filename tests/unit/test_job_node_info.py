"""JobInfo/NodeInfo/pod-resource tests (port of reference
api/{job_info,node_info,pod_info}_test.go)."""

import pytest

from kube_batch_tpu.api import (
    Container,
    JobInfo,
    NodeInfo,
    PodPhase,
    TaskInfo,
    TaskStatus,
    build_resource_list,
    get_pod_resource_request,
)
from kube_batch_tpu.utils.test_utils import build_node, build_pod


def mk_task(name, node="", phase=PodPhase.PENDING, cpu="1", group="pg1"):
    pod = build_pod(
        "ns", name, node, phase, build_resource_list(cpu=cpu, memory="1Gi"), group
    )
    return TaskInfo(pod)


class TestPodResource:
    def test_sum_of_containers(self):
        pod = build_pod("ns", "p", "", PodPhase.PENDING, {})
        pod.spec.containers = [
            Container(requests=build_resource_list(cpu="1", memory="1Gi")),
            Container(requests=build_resource_list(cpu="2", memory="1Gi")),
        ]
        r = get_pod_resource_request(pod)
        assert r.milli_cpu == 3000
        assert r.memory == 2 * 2**30

    def test_init_container_max_rule(self):
        # reference pod_info.go:56: request = max(sum(containers), each init)
        pod = build_pod("ns", "p", "", PodPhase.PENDING, {})
        pod.spec.containers = [
            Container(requests=build_resource_list(cpu="1", memory="1Gi"))
        ]
        pod.spec.init_containers = [
            Container(requests=build_resource_list(cpu="4", memory="10Mi"))
        ]
        r = get_pod_resource_request(pod)
        assert r.milli_cpu == 4000  # init container dominates cpu
        assert r.memory == 2**30  # main containers dominate memory


class TestTaskInfo:
    def test_status_from_phase(self):
        assert mk_task("a").status == TaskStatus.PENDING
        assert mk_task("b", node="n1", phase=PodPhase.RUNNING).status == TaskStatus.RUNNING
        assert mk_task("c", node="n1").status == TaskStatus.BOUND

    def test_releasing_on_deletion(self):
        t = mk_task("a", node="n1", phase=PodPhase.RUNNING)
        t.pod.metadata.deletion_timestamp = 1.0
        assert TaskInfo(t.pod).status == TaskStatus.RELEASING

    def test_job_key_namespaced(self):
        assert mk_task("a").job == "ns/pg1"

    def test_default_priority(self):
        assert mk_task("a").priority == 1

    def test_best_effort(self):
        pod = build_pod("ns", "be", "", PodPhase.PENDING, {})
        assert TaskInfo(pod).best_effort


class TestJobInfo:
    def test_add_task_indexes_by_status(self):
        # reference job_info_test.go:35 (AddTaskInfo)
        t1 = mk_task("t1")
        t2 = mk_task("t2", node="n1", phase=PodPhase.RUNNING)
        job = JobInfo("ns/pg1", t1, t2)
        assert set(job.tasks) == {t1.uid, t2.uid}
        assert t1.uid in job.task_status_index[TaskStatus.PENDING]
        assert t2.uid in job.task_status_index[TaskStatus.RUNNING]
        assert job.allocated.milli_cpu == 1000  # only the running task

    def test_delete_task(self):
        # reference job_info_test.go:103 (DeleteTaskInfo)
        t1, t2 = mk_task("t1"), mk_task("t2", node="n1", phase=PodPhase.RUNNING)
        job = JobInfo("ns/pg1", t1, t2)
        job.delete_task_info(t1)
        assert t1.uid not in job.tasks
        assert TaskStatus.PENDING not in job.task_status_index
        assert job.total_request.milli_cpu == 1000

    def test_update_task_status_moves_index(self):
        t1 = mk_task("t1")
        job = JobInfo("ns/pg1", t1)
        job.update_task_status(t1, TaskStatus.ALLOCATED)
        assert TaskStatus.PENDING not in job.task_status_index
        assert t1.uid in job.task_status_index[TaskStatus.ALLOCATED]
        assert job.allocated.milli_cpu == 1000

    def test_readiness(self):
        tasks = [mk_task(f"t{i}") for i in range(3)]
        job = JobInfo("ns/pg1", *tasks)
        job.min_available = 2
        assert not job.ready()
        job.update_task_status(tasks[0], TaskStatus.ALLOCATED)
        job.update_task_status(tasks[1], TaskStatus.PIPELINED)
        assert job.ready_task_num() == 1
        assert job.waiting_task_num() == 1
        assert not job.ready()
        assert job.pipelined()
        job.update_task_status(tasks[1], TaskStatus.ALLOCATED)
        assert job.ready()

    def test_valid_task_num_excludes_failed(self):
        tasks = [mk_task(f"t{i}") for i in range(2)]
        job = JobInfo("ns/pg1", *tasks)
        job.update_task_status(tasks[0], TaskStatus.FAILED)
        assert job.valid_task_num() == 1

    def test_bulk_update_duplicate_tasks_not_merged_as_bucket(self):
        # [a, a] vs bucket {a, b} passes the length test; the fast path
        # must still reject it, or b gets dragged to the target bucket
        # without a status write and a's resreq double-counts on a
        # flipping transition.
        a, b = mk_task("a"), mk_task("b")
        job = JobInfo("ns/pg1", a, b)
        job.update_tasks_status([a, a], TaskStatus.ALLOCATED)
        assert a.status == TaskStatus.ALLOCATED
        assert b.status == TaskStatus.PENDING
        assert b.uid in job.task_status_index[TaskStatus.PENDING]
        assert b.uid not in job.task_status_index[TaskStatus.ALLOCATED]
        assert job.allocated.milli_cpu == 1000  # a counted once

    def test_bulk_update_whole_bucket_fast_path(self):
        tasks = [mk_task(f"t{i}") for i in range(3)]
        job = JobInfo("ns/pg1", *tasks)
        job.update_tasks_status(list(tasks), TaskStatus.ALLOCATED)
        assert TaskStatus.PENDING not in job.task_status_index
        assert all(t.status == TaskStatus.ALLOCATED for t in tasks)
        assert job.allocated.milli_cpu == 3000

    def test_clone_is_deep(self):
        t1 = mk_task("t1")
        job = JobInfo("ns/pg1", t1)
        c = job.clone()
        c.update_task_status(c.tasks[t1.uid], TaskStatus.ALLOCATED)
        assert job.tasks[t1.uid].status == TaskStatus.PENDING


class TestNodeInfo:
    def make_node(self, cpu="8", mem="8Gi"):
        return NodeInfo(build_node("n1", build_resource_list(cpu=cpu, memory=mem)))

    def test_add_remove_task(self):
        # reference node_info_test.go:35 (AddTask) / :102 (RemoveTask)
        ni = self.make_node()
        t = mk_task("t1", node="n1", phase=PodPhase.RUNNING)
        ni.add_task(t)
        assert ni.idle.milli_cpu == 7000
        assert ni.used.milli_cpu == 1000
        ni.remove_task(t)
        assert ni.idle.milli_cpu == 8000
        assert ni.used.milli_cpu == 0

    def test_add_duplicate_raises(self):
        ni = self.make_node()
        t = mk_task("t1", node="n1", phase=PodPhase.RUNNING)
        ni.add_task(t)
        with pytest.raises(ValueError):
            ni.add_task(t)

    def test_releasing_accounting(self):
        # Releasing: takes idle AND counts releasing (node_info.go:186-192)
        ni = self.make_node()
        t = mk_task("t1", node="n1", phase=PodPhase.RUNNING)
        t.pod.metadata.deletion_timestamp = 1.0
        rel = TaskInfo(t.pod)
        ni.add_task(rel)
        assert ni.releasing.milli_cpu == 1000
        assert ni.idle.milli_cpu == 7000
        ni.remove_task(rel)
        assert ni.releasing.milli_cpu == 0
        assert ni.idle.milli_cpu == 8000

    def test_pipelined_consumes_releasing_not_idle(self):
        # Pipelined: releasing -= resreq, idle untouched (node_info.go:193)
        ni = self.make_node()
        t = mk_task("rel", node="n1", phase=PodPhase.RUNNING)
        t.pod.metadata.deletion_timestamp = 1.0
        ni.add_task(TaskInfo(t.pod))
        p = mk_task("pipe")
        p.status = TaskStatus.PIPELINED
        ni.add_task(p)
        assert ni.releasing.milli_cpu == 0
        assert ni.idle.milli_cpu == 7000
        assert ni.used.milli_cpu == 2000

    def test_overcommit_marks_out_of_sync(self):
        ni = self.make_node(cpu="1")
        t = mk_task("big", node="n1", phase=PodPhase.RUNNING, cpu="4")
        with pytest.raises(ValueError):
            ni.add_task(t)
        assert not ni.ready()
        assert ni.state.reason == "OutOfSync"

    def test_node_holds_task_clone(self):
        # node_info.go:181-183: status change on the original must not
        # corrupt node accounting
        ni = self.make_node()
        t = mk_task("t1", node="n1", phase=PodPhase.RUNNING)
        ni.add_task(t)
        t.status = TaskStatus.RELEASING
        ni.remove_task(t)  # removes via key; uses the stored clone's status
        assert ni.idle.milli_cpu == 8000
        assert ni.releasing.milli_cpu == 0

    def test_set_node_recomputes(self):
        ni = self.make_node()
        t = mk_task("t1", node="n1", phase=PodPhase.RUNNING)
        ni.add_task(t)
        bigger = build_node("n1", build_resource_list(cpu="16", memory="8Gi"))
        ni.set_node(bigger)
        assert ni.idle.milli_cpu == 15000
        assert ni.used.milli_cpu == 1000


class TestBatchNodeAccounting:
    """NodeInfo.add_tasks / add_tasks_with_fallback invariants (r3
    review findings): strict batch path never mutates state on failure,
    duplicate keys within one batch are rejected, and the fallback
    leaves a healthy node Ready."""

    def _node(self, cpu="4"):
        return NodeInfo(build_node("n1", build_resource_list(
            cpu=cpu, memory="8Gi")))

    def _task(self, name, cpu="1"):
        from kube_batch_tpu.api import TaskInfo
        return TaskInfo(build_pod(
            "ns", name, "", PodPhase.PENDING,
            build_resource_list(cpu=cpu, memory="1Gi")))

    def test_add_tasks_matches_sequential(self):
        a, b = self._node(), self._node()
        tasks = [self._task(f"p{i}") for i in range(3)]
        a.add_tasks(tasks)
        for t in tasks:
            b.add_task(t)
        assert a.idle.milli_cpu == b.idle.milli_cpu
        assert a.used.milli_cpu == b.used.milli_cpu
        assert sorted(a.tasks) == sorted(b.tasks)

    def test_duplicate_key_in_batch_rejected_without_mutation(self):
        n = self._node()
        t = self._task("p0")
        idle_before = n.idle.milli_cpu
        with pytest.raises(ValueError):
            n.add_tasks([t, t.clone()])
        assert n.idle.milli_cpu == idle_before
        assert not n.tasks
        assert n.ready()

    def test_batch_reject_leaves_node_ready_and_unmutated(self):
        # The strict batch path must reject without poisoning the node:
        # the fallback (or a later cycle) may still use it. (Per-dim
        # arithmetic makes "aggregate rejects what the sequential chain
        # accepts" unreachable for positive requests — overshoot can
        # only happen on the final accepted step, where both checks
        # agree — so the fallback is a safety net, not a hot path.)
        n = self._node(cpu="2")
        idle_before = n.idle.milli_cpu
        with pytest.raises(ValueError):
            n.add_tasks([self._task(f"p{i}", cpu="1") for i in range(3)])
        assert n.ready()
        assert n.idle.milli_cpu == idle_before
        assert not n.tasks

    def test_genuine_overflow_marks_out_of_sync_like_reference(self):
        # A task that truly does not fit marks the node OutOfSync via the
        # sequential path (reference node_info.go:161-171) — the batch
        # fallback preserves that.
        n = self._node(cpu="2")
        tasks = [self._task(f"p{i}", cpu="1") for i in range(3)]
        placed = n.add_tasks_with_fallback(tasks)
        assert len(placed) == 2
        assert not n.ready()  # OutOfSync: accounting genuinely overflowed
