"""Pass 1: lock-order analysis (the PR 7 deadlock class, mechanical).

Builds the project lock-acquisition graph from ``with <lock>:`` /
``<lock>.acquire()`` sites and reports:

- **order cycles** — lock A held while acquiring B somewhere, and B
  held while acquiring A somewhere else (directly or through any
  resolved call chain);
- **leaf-lock violations** — acquiring ANY lock while holding a lock
  declared leaf (attr name in ``LEAF_LOCK_ATTRS``). The cache fence
  lock is leaf by design: the watchdog fences precisely when a wedged
  cycle may be deadlocked HOLDING ``cache.mutex``, so the fencing path
  joining any lock queue re-creates the PR 7 deadlock;
- **blocking work under cache.mutex** — device dispatch (calls
  resolving into the solver device modules), ``fetch``/sync calls, or
  blocking joins/waits while a lock whose attribute name is ``mutex``
  is held. One slow call under the cache mutex stalls every watch
  event, snapshot, and bind in the process;
- **self-deadlock** — re-acquiring a held non-reentrant ``Lock``.

Lock identity: ``module::Class.attr`` for ``self.X = threading.*()``
definitions, ``module::attr`` for module-level locks. Acquisition
sites resolve by (module, class, attr), then by project-unique attr
name; unresolvable sites are ignored (this is a lint — it
under-approximates rather than guessing). ``threading.Condition(X)``
aliases to X's lock; lockdebug's ``wrap_lock("name", threading.X())``
wrappers are transparent to discovery.
"""

from __future__ import annotations

import ast
from dataclasses import dataclass
from typing import Dict, List, Optional, Set, Tuple

from .callgraph import CallGraph, CallSite, get_callgraph
from .core import (
    Finding,
    FuncDef,
    Project,
    attr_chain,
    call_name,
    iter_functions,
    register_pass,
)

PASS_ID = "lock-order"

# Lock attributes that must be LEAVES: nothing may be acquired while
# one is held. _fence_lock is the PR 7 contract (see module docstring).
LEAF_LOCK_ATTRS = frozenset({"_fence_lock"})

# Calls that block (or dispatch to the device and then block) — never
# allowed while a `mutex` lock is held.
BLOCKING_CALL_NAMES = frozenset({
    "block_until_ready", "device_get", "device_put", "fetch", "result",
    "sleep", "wait", "wait_for_side_effects", "wait_for_bookkeeping",
    "bind_volumes", "wait_pod_volumes_bound", "call_with_deadline",
})

# Modules whose in-project callees count as device dispatch.
DEVICE_MODULE_SUFFIXES = (
    "solver/kernels.py", "solver/spmd.py", "solver/sharding.py",
    "solver/pallas_kernels.py", "solver/device_cache.py",
)

_LOCK_CTORS = {"Lock": "lock", "RLock": "rlock"}


@dataclass(frozen=True)
class LockDef:
    lock_id: str  # module::Class.attr | module::attr
    rel: str
    cls: Optional[str]
    attr: str
    kind: str  # lock | rlock | condition
    line: int


def _ctor_kind(expr: ast.AST) -> Optional[str]:
    """'lock'/'rlock' when ``expr`` contains a threading.Lock/RLock
    construction anywhere — including through the lockdebug
    ``wrap_lock(name)`` wrapper, whose default factory is a plain
    Lock (no visible threading ctor)."""
    for node in ast.walk(expr):
        if isinstance(node, ast.Call):
            name = call_name(node)
            if name in _LOCK_CTORS:
                return _LOCK_CTORS[name]
            if name == "wrap_lock" and len(node.args) < 2 and not any(
                kw.arg == "lock" for kw in node.keywords
            ):
                return "lock"
    return None


def _condition_base(expr: ast.AST) -> Optional[ast.Call]:
    for node in ast.walk(expr):
        if isinstance(node, ast.Call) and call_name(node) == "Condition":
            return node
    return None


class LockIndex:
    def __init__(self, project: Project):
        self.defs: List[LockDef] = []
        self.by_exact: Dict[Tuple[str, Optional[str], str], LockDef] = {}
        self.by_attr: Dict[str, List[LockDef]] = {}
        # (rel, cls, attr) of a Condition -> the (rel, cls, attr) of
        # its base lock (resolved after discovery).
        self._cond_bases: Dict[
            Tuple[str, Optional[str], str], Tuple[str, Optional[str], str]
        ] = {}
        for pf in project.files:
            self._discover(pf)

    def _discover(self, pf) -> None:
        def scan(nodes, cls: Optional[str]):
            for node in nodes:
                if isinstance(node, ast.ClassDef):
                    scan(node.body, node.name)
                elif isinstance(
                    node, (ast.FunctionDef, ast.AsyncFunctionDef)
                ):
                    scan(node.body, cls)
                elif isinstance(node, (ast.If, ast.Try, ast.With)):
                    for child in ast.iter_child_nodes(node):
                        if isinstance(child, ast.stmt):
                            scan([child], cls)
                elif isinstance(node, ast.Assign) and len(node.targets) == 1:
                    self._maybe_add(pf.rel, cls, node.targets[0],
                                    node.value, node.lineno)

        scan(pf.tree.body, None)
        # Class bodies nest methods; a `self.X = Lock()` in __init__
        # defines a lock for the ENCLOSING class, which scan() tracked
        # via the cls parameter.

    def _maybe_add(self, rel, cls, target, value, lineno) -> None:
        chain = attr_chain(target)
        if chain is None:
            return
        if len(chain) == 2 and chain[0] == "self":
            attr = chain[1]
        elif len(chain) == 1 and cls is None:
            attr = chain[0]
        else:
            return
        kind = _ctor_kind(value)
        cond = _condition_base(value)
        if cond is not None:
            # Condition(base): alias to the base lock when one is
            # named; a bare Condition() owns a private RLock.
            if cond.args:
                base = attr_chain(cond.args[0])
                if base is not None:
                    if base[0] == "self" and len(base) == 2:
                        self._cond_bases[(rel, cls, attr)] = (
                            rel, cls, base[1]
                        )
                        return
                    if len(base) == 1:
                        self._cond_bases[(rel, cls, attr)] = (
                            rel, None, base[0]
                        )
                        return
            kind = "condition"
        if kind is None:
            return
        lock_id = (
            f"{rel}::{cls}.{attr}" if cls else f"{rel}::{attr}"
        )
        d = LockDef(lock_id=lock_id, rel=rel, cls=cls, attr=attr,
                    kind=kind, line=lineno)
        self.defs.append(d)
        self.by_exact[(rel, cls, attr)] = d
        self.by_attr.setdefault(attr, []).append(d)

    def resolve(self, rel: str, cls: Optional[str],
                expr: ast.AST) -> Optional[LockDef]:
        chain = attr_chain(expr)
        if chain is None:
            return None
        if chain[0] in ("self", "cls") and len(chain) == 2:
            attr = chain[1]
            key = (rel, cls, attr)
            key = self._cond_bases.get(key, key)
            exact = self.by_exact.get(key)
            if exact is not None:
                return exact
        elif len(chain) == 1:
            attr = chain[0]
            key = self._cond_bases.get((rel, None, attr), (rel, None, attr))
            exact = self.by_exact.get(key)
            if exact is not None:
                return exact
        else:
            attr = chain[-1]
        cands = self.by_attr.get(attr, [])
        if len(cands) == 1:
            return cands[0]
        return None


@dataclass
class Edge:
    held: LockDef
    acquired: LockDef
    rel: str
    line: int
    via: str  # "" for a direct nested acquisition, else the callee


def _analyze_function(
    fd: FuncDef, locks: LockIndex
) -> Tuple[Set[str], List[Tuple[LockDef, ast.AST, Tuple[LockDef, ...]]],
           List[Tuple[CallSite, Tuple[LockDef, ...]]]]:
    """Walk one function tracking the held-lock stack.

    Returns (direct_acquire_ids, acquisitions, calls_under_locks) where
    each acquisition/call carries the held stack at its site. Nested
    defs are walked inline (a closure defined under a lock is assumed
    callable under it — conservative; allowlist the exceptions)."""
    direct: Set[str] = set()
    acquisitions: List[Tuple[LockDef, ast.AST, Tuple[LockDef, ...]]] = []
    calls: List[Tuple[CallSite, Tuple[LockDef, ...]]] = []

    def walk_expr(expr: ast.AST, held: Tuple[LockDef, ...]) -> None:
        for node in ast.walk(expr):
            if not isinstance(node, ast.Call):
                continue
            name = call_name(node)
            if name is None:
                continue
            if name == "acquire":
                target = (
                    node.func.value
                    if isinstance(node.func, ast.Attribute) else None
                )
                lock = (
                    locks.resolve(fd.rel, fd.cls, target)
                    if target is not None else None
                )
                if lock is not None:
                    direct.add(lock.lock_id)
                    acquisitions.append((lock, node, held))
                    continue
            fn = node.func
            recv_self = bare = False
            if isinstance(fn, ast.Name):
                bare = True
            elif isinstance(fn, ast.Attribute):
                recv = fn.value
                recv_self = isinstance(recv, ast.Name) and recv.id in (
                    "self", "cls"
                )
            calls.append(
                (CallSite(name=name, recv_self=recv_self, bare=bare,
                          node=node), held)
            )

    def walk_stmts(stmts, held: Tuple[LockDef, ...]) -> None:
        for stmt in stmts:
            if isinstance(stmt, ast.With):
                inner = held
                for item in stmt.items:
                    walk_expr(item.context_expr, inner)
                    lock = locks.resolve(fd.rel, fd.cls, item.context_expr)
                    if lock is not None:
                        direct.add(lock.lock_id)
                        acquisitions.append((lock, stmt, inner))
                        inner = inner + (lock,)
                walk_stmts(stmt.body, inner)
            elif isinstance(stmt, (ast.FunctionDef, ast.AsyncFunctionDef)):
                walk_stmts(stmt.body, held)
            elif isinstance(stmt, ast.ClassDef):
                walk_stmts(stmt.body, held)
            elif isinstance(
                stmt, (ast.If, ast.While, ast.For, ast.AsyncFor)
            ):
                for expr in ast.iter_child_nodes(stmt):
                    if not isinstance(expr, ast.stmt):
                        walk_expr(expr, held)
                walk_stmts(getattr(stmt, "body", []), held)
                walk_stmts(getattr(stmt, "orelse", []), held)
            elif isinstance(stmt, ast.Try):
                walk_stmts(stmt.body, held)
                for handler in stmt.handlers:
                    walk_stmts(handler.body, held)
                walk_stmts(stmt.orelse, held)
                walk_stmts(stmt.finalbody, held)
            else:
                walk_expr(stmt, held)

    walk_stmts(fd.node.body, ())
    return direct, acquisitions, calls


def _is_blocking_join(site: CallSite) -> bool:
    """``X.join()`` / ``X.join(timeout)`` is a thread join;
    ``", ".join(parts)`` is string formatting. Disambiguate by arity
    and argument shape."""
    if site.name != "join":
        return False
    args = site.node.args
    if len(args) == 0:
        return True
    if len(args) == 1 and isinstance(args[0], (ast.Constant, ast.Name)):
        if isinstance(args[0], ast.Constant):
            return isinstance(args[0].value, (int, float))
    return bool(site.node.keywords)


@register_pass(PASS_ID)
def run(project: Project) -> List[Finding]:
    locks = LockIndex(project)
    graph = get_callgraph(project)
    findings: List[Finding] = []

    per_func: Dict[str, Tuple] = {}
    direct_acquires: Dict[str, Set[str]] = {}
    for pf in project.files:
        for fd in iter_functions(pf):
            analyzed = _analyze_function(fd, locks)
            per_func[fd.key] = (fd, analyzed)
            direct_acquires[fd.key] = analyzed[0]

    may_acquire = graph.transitive_marks(direct_acquires)
    by_id = {d.lock_id: d for d in locks.defs}

    edges: Dict[Tuple[str, str], Edge] = {}

    def add_edge(held: LockDef, acquired: LockDef, rel: str, line: int,
                 via: str) -> None:
        key = (held.lock_id, acquired.lock_id)
        if key not in edges:
            edges[key] = Edge(held=held, acquired=acquired, rel=rel,
                              line=line, via=via)

    for key, (fd, (direct, acquisitions, calls)) in per_func.items():
        entry = graph.entries.get(fd.key)
        for lock, node, held in acquisitions:
            for h in held:
                if h.lock_id == lock.lock_id:
                    if lock.kind == "lock":
                        findings.append(Finding(
                            PASS_ID, fd.rel, node.lineno,
                            f"self-deadlock: non-reentrant lock "
                            f"{lock.lock_id} re-acquired while already "
                            f"held in {fd.qualname}",
                        ))
                    continue
                add_edge(h, lock, fd.rel, node.lineno, via="")
        for site, held in calls:
            if not held or entry is None:
                continue
            callees = graph.resolve(entry, site)
            acquired_ids: Set[str] = set()
            for callee in callees:
                acquired_ids |= may_acquire.get(callee.fd.key, set())
            for lock_id in acquired_ids:
                lock = by_id[lock_id]
                for h in held:
                    if h.lock_id == lock_id:
                        continue  # reentrant/self handled at def site
                    add_edge(h, lock, fd.rel, site.node.lineno,
                             via=site.name)

    # Leaf-lock rule: nothing may be acquired while a leaf is held.
    for (held_id, acq_id), edge in sorted(edges.items()):
        if edge.held.attr in LEAF_LOCK_ATTRS:
            via = f" via {edge.via}()" if edge.via else ""
            findings.append(Finding(
                PASS_ID, edge.rel, edge.line,
                f"leaf-lock violation: {acq_id} acquired{via} while "
                f"holding leaf lock {held_id} (the fence path must "
                f"never join a lock queue — PR 7 deadlock class)",
            ))

    # Order cycles: SCCs of size >1 in the edge graph.
    findings.extend(_cycle_findings(edges))

    # Blocking/device work under a `mutex` lock.
    findings.extend(
        _mutex_blocking_findings(per_func, graph, may_acquire)
    )

    findings.sort(key=lambda f: (f.file, f.line, f.message))
    return findings


def _cycle_findings(edges: Dict[Tuple[str, str], Edge]) -> List[Finding]:
    adj: Dict[str, Set[str]] = {}
    for held_id, acq_id in edges:
        adj.setdefault(held_id, set()).add(acq_id)
        adj.setdefault(acq_id, set())

    index: Dict[str, int] = {}
    low: Dict[str, int] = {}
    on_stack: Set[str] = set()
    stack: List[str] = []
    sccs: List[List[str]] = []
    counter = [0]

    def strongconnect(v: str) -> None:
        # Iterative Tarjan (the lock graph is tiny, but recursion
        # limits are not a failure mode a linter should have).
        work = [(v, iter(sorted(adj[v])))]
        index[v] = low[v] = counter[0]
        counter[0] += 1
        stack.append(v)
        on_stack.add(v)
        while work:
            node, it = work[-1]
            advanced = False
            for w in it:
                if w not in index:
                    index[w] = low[w] = counter[0]
                    counter[0] += 1
                    stack.append(w)
                    on_stack.add(w)
                    work.append((w, iter(sorted(adj[w]))))
                    advanced = True
                    break
                if w in on_stack:
                    low[node] = min(low[node], index[w])
            if advanced:
                continue
            work.pop()
            if work:
                parent = work[-1][0]
                low[parent] = min(low[parent], low[node])
            if low[node] == index[node]:
                scc = []
                while True:
                    w = stack.pop()
                    on_stack.discard(w)
                    scc.append(w)
                    if w == node:
                        break
                sccs.append(scc)

    for v in sorted(adj):
        if v not in index:
            strongconnect(v)

    findings: List[Finding] = []
    for scc in sccs:
        if len(scc) < 2:
            continue
        members = sorted(scc)
        cycle_name = " <-> ".join(members)
        for (held_id, acq_id), edge in sorted(edges.items()):
            if held_id in scc and acq_id in scc:
                via = f" via {edge.via}()" if edge.via else ""
                findings.append(Finding(
                    PASS_ID, edge.rel, edge.line,
                    f"lock-order cycle: {held_id} held while acquiring "
                    f"{acq_id}{via}; cycle: {cycle_name}",
                ))
    return findings


def _mutex_blocking_findings(per_func, graph: CallGraph,
                             may_acquire) -> List[Finding]:
    findings: List[Finding] = []
    for key, (fd, (direct, acquisitions, calls)) in per_func.items():
        entry = graph.entries.get(fd.key)
        for site, held in calls:
            if not any(h.attr == "mutex" for h in held):
                continue
            if site.name in BLOCKING_CALL_NAMES:
                findings.append(Finding(
                    PASS_ID, fd.rel, site.node.lineno,
                    f"blocking call {site.name}() while holding "
                    f"cache.mutex in {fd.qualname} (device sync / wait "
                    f"under the cache mutex stalls every watch event "
                    f"and bind in the process)",
                ))
                continue
            if _is_blocking_join(site):
                findings.append(Finding(
                    PASS_ID, fd.rel, site.node.lineno,
                    f"thread join() while holding cache.mutex in "
                    f"{fd.qualname}",
                ))
                continue
            if entry is None:
                continue
            for callee in graph.resolve(entry, site):
                if callee.fd.rel.replace("\\", "/").endswith(
                    DEVICE_MODULE_SUFFIXES
                ):
                    findings.append(Finding(
                        PASS_ID, fd.rel, site.node.lineno,
                        f"device dispatch {site.name}() "
                        f"({callee.fd.key}) while holding cache.mutex "
                        f"in {fd.qualname}",
                    ))
                    break
    return findings
