"""QueueInfo: scheduling view of a tenant queue.

Mirrors reference pkg/scheduler/api/queue_info.go (:73 QueueInfo{UID,Name,
Weight,Queue}; Spec.Weight/Capability :63-66).
"""

from __future__ import annotations

from .objects import Queue

QueueID = str


class QueueInfo:
    def __init__(self, queue: Queue):
        # UID is the queue NAME (reference queue_info.go:77: jobs reference
        # queues by name, and the cache keys queues by name too).
        self.uid: QueueID = queue.name
        self.name = queue.name
        self.weight = queue.spec.weight
        self.queue = queue

    def clone(self) -> "QueueInfo":
        return QueueInfo(self.queue)

    def __repr__(self) -> str:
        return f"Queue ({self.name}): weight {self.weight}"
