"""In-memory fake Kubernetes API server.

A stdlib HTTP server speaking just enough of the k8s REST protocol —
JSON lists, streaming ?watch=true, the Binding subresource, Lease CRUD
with optimistic concurrency, status PATCHes — to drive the whole
scheduler end-to-end through the real KubeCluster adapter. This is the
repo's kubemark analog (SURVEY.md §4 tier 4: simulated kubelets, real
scheduler): the Binding subresource flips pods to Running like a hollow
kubelet. Used by the unit/e2e suites and by tools/run_e2e.py (the
hack/run-e2e-kind.sh analog).
"""

from __future__ import annotations

import json
import queue
import threading
from http.server import BaseHTTPRequestHandler, ThreadingHTTPServer

from ..api.objects import SCHEDULING_GROUP as GROUP


def pod_doc(name, ns="default", cpu="500m", group=None, phase="Pending"):
    meta = {"name": name, "namespace": ns, "uid": f"uid-{ns}-{name}"}
    if group:
        meta["annotations"] = {"scheduling.k8s.io/group-name": group}
    return {
        "apiVersion": "v1", "kind": "Pod", "metadata": meta,
        "spec": {"containers": [
            {"name": "main", "resources": {"requests": {
                "cpu": cpu, "memory": "256Mi",
            }}},
        ]},
        "status": {"phase": phase},
    }


def node_doc(name, cpu="4", pods="20"):
    return {
        "apiVersion": "v1", "kind": "Node",
        "metadata": {"name": name, "uid": f"uid-{name}"},
        "status": {
            "allocatable": {"cpu": cpu, "memory": "8Gi", "pods": pods},
            "capacity": {"cpu": cpu, "memory": "8Gi", "pods": pods},
        },
    }


class FakeKube:
    """In-memory k8s API server: lists, watches, binding, status patches."""

    PATHS = {
        "/api/v1/pods": "Pod",
        "/api/v1/nodes": "Node",
        f"/apis/{GROUP}/v1alpha1/podgroups": "PodGroup",
        f"/apis/{GROUP}/v1alpha1/queues": "Queue",
        "/apis/scheduling.k8s.io/v1/priorityclasses": "PriorityClass",
        "/apis/policy/v1/poddisruptionbudgets": "PodDisruptionBudget",
        "/api/v1/persistentvolumeclaims": "PersistentVolumeClaim",
    }

    # namespaced item-GET collection segment -> kind
    COLLECTIONS = {
        "pods": "Pod",
        "persistentvolumeclaims": "PersistentVolumeClaim",
    }

    def __init__(self):
        self.objects = {kind: {} for kind in self.PATHS.values()}
        self.subscribers = {kind: [] for kind in self.PATHS.values()}
        self.bindings = []
        self.status_patches = []
        self.leases = {}
        self.lock = threading.RLock()
        self.rv = 0
        self.last_auth = None      # Authorization header of last request
        self.reject_token = None   # bearer token to 401 (auth tests)
        # Failure injection (error-path fixtures): callable
        # (method, path) -> None | (code, status_doc). Return a k8s
        # Status document shaped like a real apiserver error to have the
        # request answered with it instead of being served.
        self.request_hook = None
        # Bind-failure injection (the sim/fault-run seam, narrower than
        # request_hook): callable (pod_key, hostname) -> None |
        # (code, status_doc). A non-None return answers the Binding POST
        # with that error WITHOUT mutating the pod — the scheduler's
        # resync path must recover. Decisions should be pure functions
        # of (pod, attempt) so a recorded run replays bit-identically.
        self.bind_failure_hook = None

        fake = self

        class Handler(BaseHTTPRequestHandler):
            protocol_version = "HTTP/1.0"  # close-delimited watch streams

            def log_message(self, *a):
                pass

            def _json(self, code, body):
                data = json.dumps(body).encode()
                self.send_response(code)
                self.send_header("Content-Type", "application/json")
                self.send_header("Content-Length", str(len(data)))
                self.end_headers()
                self.wfile.write(data)

            def _read_body(self):
                n = int(self.headers.get("Content-Length", 0))
                return json.loads(self.rfile.read(n)) if n else {}

            def _auth_gate(self):
                fake.last_auth = self.headers.get("Authorization")
                if (
                    fake.reject_token is not None
                    and fake.last_auth == f"Bearer {fake.reject_token}"
                ):
                    self._json(401, {"kind": "Status", "code": 401})
                    return False
                hook = fake.request_hook
                if hook is not None:
                    injected = hook(self.command, self.path)
                    if injected is not None:
                        code, body = injected
                        self._json(code, body)
                        return False
                return True

            def do_GET(self):
                if not self._auth_gate():
                    return
                path, _, qs = self.path.partition("?")
                kind = fake.PATHS.get(path)
                if kind is None:
                    if "/leases/" in path:
                        with fake.lock:
                            lease = fake.leases.get(path)
                        if lease is None:
                            self._json(404, {"kind": "Status", "code": 404})
                        else:
                            self._json(200, lease)
                        return
                    # Item GET: /api/v1/namespaces/{ns}/{collection}/{name}
                    if "/namespaces/" in path:
                        parts = path.split("/")
                        ns, coll, name = parts[4], parts[5], parts[6]
                        obj_kind = fake.COLLECTIONS.get(coll, "Pod")
                        with fake.lock:
                            obj = fake.objects[obj_kind].get(f"{ns}/{name}")
                        if obj is None:
                            self._json(404, {"kind": "Status", "code": 404})
                        else:
                            self._json(200, obj)
                        return
                    self._json(404, {"kind": "Status", "code": 404})
                    return
                if "watch=true" in qs:
                    self.send_response(200)
                    self.send_header("Content-Type", "application/json")
                    self.end_headers()
                    q = queue.Queue()
                    with fake.lock:
                        fake.subscribers[kind].append(q)
                    try:
                        while True:
                            try:
                                event = q.get(timeout=0.2)
                            except queue.Empty:
                                continue
                            if event is None:
                                return
                            self.wfile.write(
                                (json.dumps(event) + "\n").encode()
                            )
                            self.wfile.flush()
                    except (BrokenPipeError, ConnectionResetError):
                        return
                with fake.lock:
                    # Sorted by key, NOT insertion order: list responses
                    # must not depend on the interleaving of concurrent
                    # creates, or a recorded scheduler run (whose cache
                    # ingest order follows the initial list) would not
                    # replay bit-identically against the same state.
                    items = [
                        fake.objects[kind][k]
                        for k in sorted(fake.objects[kind])
                    ]
                    rv = str(fake.rv)
                if path.startswith("/api/v1"):
                    api_version = "v1"
                else:
                    parts = path.split("/")
                    api_version = f"{parts[2]}/{parts[3]}"
                self._json(200, {
                    "apiVersion": api_version, "kind": f"{kind}List",
                    "metadata": {"resourceVersion": rv},
                    "items": items,
                })

            def do_POST(self):
                if not self._auth_gate():
                    return
                if self.path.endswith("/leases"):
                    body = self._read_body()
                    name = body["metadata"]["name"]
                    key = f"{self.path}/{name}"
                    with fake.lock:
                        if key in fake.leases:
                            self._json(409, {"kind": "Status", "code": 409})
                            return
                        fake.rv += 1
                        body["metadata"]["resourceVersion"] = str(fake.rv)
                        fake.leases[key] = body
                    self._json(201, body)
                    return
                if self.path.endswith("/binding"):
                    body = self._read_body()
                    parts = self.path.split("/")
                    ns, name = parts[4], parts[6]
                    hostname = body.get("target", {}).get("name", "")
                    hook = fake.bind_failure_hook
                    if hook is not None:
                        injected = hook(f"{ns}/{name}", hostname)
                        if injected is not None:
                            code, doc = injected
                            self._json(code, doc)
                            return
                    with fake.lock:
                        pod = fake.objects["Pod"].get(f"{ns}/{name}")
                        if pod is None:
                            self._json(404, {"code": 404})
                            return
                        pod["spec"]["nodeName"] = hostname
                        pod["status"]["phase"] = "Running"  # hollow kubelet
                        fake.bindings.append((f"{ns}/{name}", hostname))
                        fake._emit("Pod", "MODIFIED", pod)
                    self._json(201, {"kind": "Status", "status": "Success"})
                    return
                if "/events" in self.path:
                    self._json(201, {"kind": "Status", "status": "Success"})
                    return
                self._json(404, {"code": 404})

            def do_PATCH(self):
                if not self._auth_gate():
                    return
                body = self._read_body()
                with fake.lock:
                    fake.status_patches.append((self.path, body))
                self._json(200, {"kind": "Status", "status": "Success"})

            def do_PUT(self):
                if not self._auth_gate():
                    return
                if "/leases/" not in self.path:
                    self._json(404, {"code": 404})
                    return
                body = self._read_body()
                with fake.lock:
                    stored = fake.leases.get(self.path)
                    if stored is None:
                        self._json(404, {"code": 404})
                        return
                    # Optimistic concurrency: resourceVersion must match.
                    if (
                        body.get("metadata", {}).get("resourceVersion")
                        != stored["metadata"]["resourceVersion"]
                    ):
                        self._json(409, {"kind": "Status", "code": 409})
                        return
                    fake.rv += 1
                    body["metadata"]["resourceVersion"] = str(fake.rv)
                    fake.leases[self.path] = body
                self._json(200, body)

            def do_DELETE(self):
                if not self._auth_gate():
                    return
                parts = self.path.split("/")
                ns, name = parts[4], parts[6]
                with fake.lock:
                    pod = fake.objects["Pod"].pop(f"{ns}/{name}", None)
                    if pod is not None:
                        fake._emit("Pod", "DELETED", pod)
                self._json(200, {"kind": "Status", "status": "Success"})

        self.server = ThreadingHTTPServer(("127.0.0.1", 0), Handler)
        self.thread = threading.Thread(
            target=self.server.serve_forever, daemon=True
        )
        self.thread.start()

    @property
    def url(self):
        host, port = self.server.server_address
        return f"http://{host}:{port}"

    def _key(self, doc):
        m = doc["metadata"]
        ns = m.get("namespace", "")
        return f"{ns}/{m['name']}" if ns else m["name"]

    def _emit(self, kind, etype, doc):
        self.rv += 1
        doc.setdefault("metadata", {})["resourceVersion"] = str(self.rv)
        for q in self.subscribers[kind]:
            q.put({"type": etype, "object": doc})

    def create(self, kind, doc):
        with self.lock:
            self.objects[kind][self._key(doc)] = doc
            self._emit(kind, "ADDED", doc)

    def remove_silently(self, kind, key):
        """Delete an object WITHOUT emitting a watch event — simulates a
        deletion the client's watch missed (e.g. during a 410 gap)."""
        with self.lock:
            self.objects[kind].pop(key, None)

    def emit_error(self, kind, code, reason="Expired"):
        """Send a watch ERROR event shaped like a real apiserver's (a
        Status document as the object), e.g. 410 Gone after resource-
        version expiry."""
        with self.lock:
            for q in self.subscribers[kind]:
                q.put({
                    "type": "ERROR",
                    "object": {
                        "kind": "Status", "apiVersion": "v1",
                        "status": "Failure", "reason": reason,
                        "code": code,
                        "message": f"too old resource version ({reason})",
                    },
                })

    def kick_watchers(self, kind):
        """Close every open watch stream for ``kind`` (server-side
        disconnect); clients are expected to reconnect from their last
        resourceVersion."""
        with self.lock:
            for q in self.subscribers[kind]:
                q.put(None)
            self.subscribers[kind] = []

    def close(self):
        with self.lock:
            for qs in self.subscribers.values():
                for q in qs:
                    q.put(None)
        self.server.shutdown()


def pvc_doc(name, ns="default", phase="Pending"):
    return {
        "apiVersion": "v1", "kind": "PersistentVolumeClaim",
        "metadata": {"name": name, "namespace": ns,
                     "uid": f"uid-pvc-{ns}-{name}"},
        "spec": {},
        "status": {"phase": phase},
    }


def pod_with_claim_doc(name, claim, ns="default"):
    doc = pod_doc(name, ns=ns, group=None)
    doc["spec"]["volumes"] = [
        {"name": claim, "persistentVolumeClaim": {"claimName": claim}},
    ]
    return doc
