"""k8s-manifest compatibility: load kube-batch CRD YAML directly.

A kube-batch user's existing manifests — PodGroup and Queue under API
group ``scheduling.incubator.k8s.io`` in either ``v1alpha1`` or
``v1alpha2`` (the reference ships both versions with identical schemas,
pkg/apis/scheduling/{v1alpha1,v1alpha2}/types.go; see config/crds/*.yaml
and example/job.yaml), plus core ``v1`` Pod/Node/PriorityClass — load
straight into the in-process cluster. This is the user-facing API surface
of the reference (SURVEY.md §2 row 25); the generated clientset/informers
(row 26) have no standalone analog beyond the ClusterAPI watch contract.

Multi-document YAML is supported; unknown kinds raise.
"""

from __future__ import annotations

from typing import Iterable, List, Optional, Tuple

import yaml

from ..api import GROUP_NAME_ANNOTATION_KEY, PodPhase, PriorityClass
from ..api.objects import (
    SCHEDULING_GROUP,  # re-exported: the loader's public group constant
    Affinity,
    Container,
    Node,
    NodeStatus,
    ObjectMeta,
    Pod,
    PodDisruptionBudget,
    PodGroup,
    PodGroupSpec,
    PodSpec,
    Queue,
    QueueSpec,
    Taint,
    Toleration,
)
from ..cluster import InProcessCluster

SUPPORTED_VERSIONS = ("v1alpha1", "v1alpha2")


def _meta(doc: dict) -> ObjectMeta:
    m = doc.get("metadata", {}) or {}
    owner_uid = None
    for ref in m.get("ownerReferences", []) or []:
        if ref.get("controller"):
            owner_uid = ref.get("uid") or ref.get("name")
            break
    return ObjectMeta(
        name=m.get("name", ""),
        namespace=m.get("namespace", ""),
        uid=m.get("uid", "") or f"{m.get('namespace', '')}-{m.get('name', '')}",
        labels=dict(m.get("labels", {}) or {}),
        annotations=dict(m.get("annotations", {}) or {}),
        owner_uid=owner_uid,
    )


def _resource_list(d) -> dict:
    return {str(k): str(v) for k, v in (d or {}).items()}


def _pod_group(doc: dict) -> PodGroup:
    spec = doc.get("spec", {}) or {}
    return PodGroup(
        metadata=_meta(doc),
        spec=PodGroupSpec(
            min_member=int(spec.get("minMember", 1)),
            queue=spec.get("queue", ""),
            priority_class_name=spec.get("priorityClassName", ""),
        ),
    )


def _queue(doc: dict) -> Queue:
    spec = doc.get("spec", {}) or {}
    capability = spec.get("capability")
    return Queue(
        metadata=_meta(doc),
        spec=QueueSpec(
            weight=int(spec.get("weight", 1)),
            capability=_resource_list(capability) if capability else None,
        ),
    )


def _toleration(t: dict) -> Toleration:
    return Toleration(
        key=t.get("key", ""),
        operator=t.get("operator", "Equal"),
        value=str(t.get("value", "")),
        effect=t.get("effect", ""),
    )


def _match_expressions(exprs) -> List[dict]:
    """k8s matchExpressions -> the internal expression-dict form (shared
    by node affinity terms, preferences, and pod-affinity selectors)."""
    return [
        {
            "key": e.get("key"),
            "operator": e.get("operator", "In"),
            "values": list(e.get("values") or []),
        }
        for e in exprs or []
    ]


def _affinity(a: dict) -> Affinity:
    node_req = None
    node_pref = None
    node_aff = (a or {}).get("nodeAffinity") or {}
    required = node_aff.get(
        "requiredDuringSchedulingIgnoredDuringExecution"
    ) or {}
    terms = required.get("nodeSelectorTerms") or []
    if terms:
        # Term structure is preserved: k8s ORs across nodeSelectorTerms and
        # ANDs within a term's matchExpressions (vendored reference
        # predicates nodeMatchesNodeSelectorTerms: "if one of the terms is
        # satisfied"). Flattening would turn zone-a OR zone-b into an
        # unsatisfiable conjunction.
        node_req = []
        for t in terms:
            if t.get("matchFields"):
                raise ValueError(
                    "nodeSelectorTerms.matchFields is not supported; "
                    "use matchExpressions"
                )
            node_req.append(
                _match_expressions(t.get("matchExpressions"))
            )
    preferred = node_aff.get(
        "preferredDuringSchedulingIgnoredDuringExecution"
    ) or []
    if preferred:
        node_pref = [
            {
                "weight": p.get("weight", 1),
                "expressions": _match_expressions(
                    (p.get("preference", {}) or {}).get("matchExpressions")
                ),
            }
            for p in preferred
        ]

    def _pod_terms(section: str):
        sec = (a or {}).get(section) or {}
        req = sec.get("requiredDuringSchedulingIgnoredDuringExecution") or []
        out = []
        for term in req:
            topo = term.get("topologyKey", "kubernetes.io/hostname")
            if topo != "kubernetes.io/hostname":
                # The in-process evaluator's topology domain is the node
                # (reference predicates.go:252-262 with node-level
                # NodeInfo); a zone/rack key would silently change which
                # pods count as co-located.
                raise ValueError(
                    f"unsupported {section} topologyKey {topo!r} "
                    "(only kubernetes.io/hostname)"
                )
            sel = term.get("labelSelector", {}) or {}
            unknown = set(sel) - {"matchLabels", "matchExpressions"}
            if unknown:
                raise ValueError(
                    f"unsupported {section} labelSelector fields {sorted(unknown)}"
                )
            parsed = {
                "label_selector": dict(sel.get("matchLabels", {}) or {})
            }
            exprs = sel.get("matchExpressions") or []
            if exprs:
                parsed["match_expressions"] = _match_expressions(exprs)
            out.append(parsed)
        return out or None

    return Affinity(
        node_required=node_req,
        node_preferred=node_pref,
        pod_affinity=_pod_terms("podAffinity"),
        pod_anti_affinity=_pod_terms("podAntiAffinity"),
    )


def _pod(doc: dict) -> Pod:
    spec = doc.get("spec", {}) or {}
    status = doc.get("status", {}) or {}
    containers = []
    ports: List[int] = []
    for c in spec.get("containers", []) or []:
        requests = (
            (c.get("resources", {}) or {}).get("requests", {}) or {}
        )
        cports = [
            int(p.get("hostPort"))
            for p in c.get("ports", []) or []
            if p.get("hostPort")
        ]
        containers.append(Container(
            name=c.get("name", "main"),
            requests=_resource_list(requests),
            ports=cports,
        ))
        ports.extend(cports)
    affinity = spec.get("affinity")
    claims = [
        v["persistentVolumeClaim"]["claimName"]
        for v in spec.get("volumes", []) or []
        if v.get("persistentVolumeClaim", {}).get("claimName")
    ]
    pod = Pod(
        metadata=_meta(doc),
        spec=PodSpec(
            node_name=spec.get("nodeName", ""),
            node_selector=dict(spec.get("nodeSelector", {}) or {}),
            affinity=_affinity(affinity) if affinity else None,
            tolerations=[
                _toleration(t) for t in spec.get("tolerations", []) or []
            ],
            containers=containers or [Container()],
            priority=spec.get("priority"),
            scheduler_name=spec.get(
                "schedulerName", PodSpec().scheduler_name
            ),
            volume_claims=claims,
        ),
    )
    pod.status.phase = status.get("phase", PodPhase.PENDING)
    return pod


def _node(doc: dict) -> Node:
    status = doc.get("status", {}) or {}
    spec = doc.get("spec", {}) or {}
    allocatable = _resource_list(
        status.get("allocatable") or status.get("capacity")
    )
    capacity = _resource_list(status.get("capacity") or allocatable)
    node = Node(
        metadata=_meta(doc),
        status=NodeStatus(allocatable=allocatable, capacity=capacity),
    )
    node.spec.unschedulable = bool(spec.get("unschedulable", False))
    node.spec.taints = [
        Taint(
            key=t.get("key", ""),
            value=str(t.get("value", "")),
            effect=t.get("effect", ""),
        )
        for t in spec.get("taints", []) or []
    ]
    return node


def _pdb(doc: dict) -> Optional[PodDisruptionBudget]:
    """A PDB acts as a legacy gang source ONLY when it has a controller
    owner and an absolute minAvailable (reference event_handlers.go:662-700
    keys the job by the controller UID). Ordinary disruption budgets —
    label-selector based, ownerless, or percentage minAvailable — are not
    gang specs; they load as a no-op instead of failing the manifest."""
    meta = _meta(doc)
    spec = doc.get("spec", {}) or {}
    min_available = spec.get("minAvailable", 1)
    if not meta.owner_uid:
        return None
    if isinstance(min_available, str):
        if min_available.endswith("%"):
            return None
        min_available = int(min_available)
    return PodDisruptionBudget(metadata=meta, min_available=int(min_available))


def _priority_class(doc: dict) -> PriorityClass:
    return PriorityClass(
        metadata=_meta(doc),
        value=int(doc.get("value", 0)),
        global_default=bool(doc.get("globalDefault", False)),
    )


def parse_manifest(doc: dict) -> Tuple[str, object]:
    """(cluster kind, object) from one k8s manifest document."""
    api_version = doc.get("apiVersion", "")
    kind = doc.get("kind", "")
    if "/" in api_version:
        group, version = api_version.split("/", 1)
    else:
        group, version = "", api_version
    if group == SCHEDULING_GROUP:
        if version not in SUPPORTED_VERSIONS:
            raise ValueError(
                f"unsupported {SCHEDULING_GROUP} version {version!r} "
                f"(supported: {SUPPORTED_VERSIONS})"
            )
        if kind == "PodGroup":
            return "PodGroup", _pod_group(doc)
        if kind == "Queue":
            return "Queue", _queue(doc)
        raise ValueError(f"unknown kind {kind!r} in group {group}")
    if group in ("", "v1") or api_version == "v1":
        if kind == "Pod":
            return "Pod", _pod(doc)
        if kind == "Node":
            return "Node", _node(doc)
        if kind == "PriorityClass":
            return "PriorityClass", _priority_class(doc)
        if kind == "PersistentVolumeClaim":
            meta = doc.get("metadata", {}) or {}
            phase = (doc.get("status", {}) or {}).get("phase", "")
            return "PersistentVolumeClaim", {
                "namespace": meta.get("namespace", ""),
                "name": meta.get("name", ""),
                "bound": phase == "Bound",
            }
    if group == "scheduling.k8s.io" and kind == "PriorityClass":
        return "PriorityClass", _priority_class(doc)
    if group == "policy" and kind == "PodDisruptionBudget":
        pdb = _pdb(doc)
        # (None, None) = recognized but not applicable (no controller
        # owner / percentage budget): not a gang source, skip quietly.
        return ("PodDisruptionBudget", pdb) if pdb else (None, None)
    raise ValueError(f"unsupported manifest {api_version!r} kind {kind!r}")


def apply_manifests(cluster: InProcessCluster, docs: Iterable[dict]) -> int:
    """Create every manifest object in the cluster; returns the count of
    applied objects (recognized-but-skipped documents are not counted)."""
    n = 0
    for doc in docs:
        if not doc:
            continue
        kind, obj = parse_manifest(doc)
        if kind is None:
            continue
        if kind == "PersistentVolumeClaim":
            cluster.create_claim(
                obj["namespace"], obj["name"], bound=obj["bound"]
            )
        else:
            cluster.create(kind, obj)
        n += 1
    return n


def load_manifest_file(cluster: InProcessCluster, path: str) -> int:
    with open(path) as f:
        return apply_manifests(cluster, yaml.safe_load_all(f))


# Convenience: the group-name annotation a Pod uses to join a PodGroup
# (reference labels.go:21, read by job_info).
__all__ = [
    "GROUP_NAME_ANNOTATION_KEY",
    "SCHEDULING_GROUP",
    "apply_manifests",
    "load_manifest_file",
    "parse_manifest",
]
