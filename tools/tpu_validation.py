#!/usr/bin/env python
"""One-shot TPU validation runbook.

Everything in this repo that is gated on REAL TPU hardware, runnable the
moment the accelerator becomes reachable:

1. backend probe (bounded; aborts with a clear message when the tunnel
   is wedged rather than hanging),
2. bench.py at every config with the jax kernel on device (the headline
   BASELINE.md target: <100 ms at 50k x 5k, >=10x the native loop),
3. Pallas fused-bid kernel: compiled (non-interpret) parity vs the jnp
   chain, then an A/B of KBT_PALLAS=1 vs the default path at the
   headline scale — the data for deciding whether Pallas becomes the
   default (VERDICT r1 item 5).

Writes one JSON report (default tpu_validation.json) and prints a
summary. Usage: python tools/tpu_validation.py [--out FILE] [--skip-bench]
"""

import argparse
import json
import os
import subprocess
import sys
import time

REPO = os.path.dirname(os.path.dirname(os.path.abspath(__file__)))
sys.path.insert(0, REPO)


def probe():
    from kube_batch_tpu.utils.backend import probe_default_backend

    return probe_default_backend(timeout=120, attempts=2, backoff=15,
                                 total_budget=270)


def run_bench(config, env_extra=None, timeout=900):
    env = dict(os.environ)
    env.update(env_extra or {})
    try:
        proc = subprocess.run(
            [sys.executable, "bench.py", "--config", config],
            capture_output=True, text=True, timeout=timeout, cwd=REPO,
            env=env,
        )
    except subprocess.TimeoutExpired:
        # One slow step must not lose the report (docstring contract).
        return {"error": f"timeout after {timeout}s"}
    line = (proc.stdout.strip().splitlines() or [""])[-1]
    try:
        return json.loads(line)
    except ValueError:
        return {"error": proc.stderr[-1000:], "rc": proc.returncode}


def run_pallas_parity(timeout=600):
    """Compiled (non-interpret) pallas_bid parity on the device."""
    code = """
import json
import numpy as np
import jax.numpy as jnp
import sys
sys.path.insert(0, %r)
from tests.solver.test_pallas import jnp_reference_bid, _random_case
from kube_batch_tpu.solver.pallas_kernels import pallas_bid, TILE_T

ok = True
for seed in (0, 1, 2):
    case = _random_case(seed, T=2 * TILE_T, N=256)
    args = (case["task_fit"], case["task_req"], case["task_ok"],
            case["feas"], case["idle"], case["cap"], case["cap_ok"],
            case["eps"], case["lr_w"], case["br_w"])
    bid_p, any_p = pallas_bid(*args, interpret=False)  # compiled on TPU
    bid_r, any_r = jnp_reference_bid(*args)
    ok &= bool((np.asarray(bid_p) == np.asarray(bid_r)).all())
    ok &= bool((np.asarray(any_p) == np.asarray(any_r)).all())
print(json.dumps({"pallas_compiled_parity": ok}))
""" % REPO
    try:
        proc = subprocess.run(
            [sys.executable, "-c", code], capture_output=True, text=True,
            timeout=timeout, cwd=REPO,
        )
    except subprocess.TimeoutExpired:
        return {"error": f"timeout after {timeout}s"}
    line = (proc.stdout.strip().splitlines() or [""])[-1]
    try:
        return json.loads(line)
    except ValueError:
        return {"error": proc.stderr[-1000:], "rc": proc.returncode}


def main():
    ap = argparse.ArgumentParser()
    ap.add_argument("--out", default="tpu_validation.json")
    ap.add_argument("--skip-bench", action="store_true")
    args = ap.parse_args()

    report = {"started": time.strftime("%Y-%m-%dT%H:%M:%SZ", time.gmtime())}
    n = probe()
    report["devices"] = n
    if n == 0:
        report["status"] = "tunnel unreachable; nothing hardware-gated ran"
        print(json.dumps(report, indent=2))
        with open(args.out, "w") as f:
            json.dump(report, f, indent=2)
        return 1

    if not args.skip_bench:
        report["bench"] = {}
        for cfg in ("small", "medium", "large"):
            # bench.py now measures full production cycles too; the
            # large config needs more runway than the old solve-only run.
            report["bench"][cfg] = run_bench(
                cfg, timeout=1500 if cfg == "large" else 900
            )
        report["bench_pallas_large"] = run_bench(
            "large", env_extra={"KBT_PALLAS": "1"}, timeout=1500
        )
    report["pallas"] = run_pallas_parity()

    large = (report.get("bench", {}) or {}).get("large", {})
    report["headline_ms"] = large.get("value")
    report["vs_baseline"] = large.get("vs_baseline")
    report["target_met"] = bool(
        isinstance(large.get("value"), (int, float))
        and large["value"] < 100
        and large.get("device") == "tpu"
    )
    with open(args.out, "w") as f:
        json.dump(report, f, indent=2)
    print(json.dumps(report, indent=2))
    return 0


if __name__ == "__main__":
    sys.exit(main())
