"""Test configuration: force JAX onto a virtual 8-device CPU mesh.

Must run before any ``import jax`` so multi-chip sharding paths can be
exercised without TPU hardware (the driver separately dry-runs the real
multi-chip path via __graft_entry__.dryrun_multichip).
"""

import os

# Force CPU even when the environment preselects a TPU platform (e.g.
# JAX_PLATFORMS=axon): unit/e2e tests must be hardware-independent; the
# benchmark harness and the driver's dryrun use the real platform.
os.environ["JAX_PLATFORMS"] = "cpu"

# A site-injected PJRT plugin (tunneled TPU) may already be registered by
# sitecustomize before this conftest runs; jax initializes every registered
# factory during backend discovery, so JAX_PLATFORMS=cpu alone does not stop
# it from dialing the (possibly unreachable) tunnel and hanging the whole
# test run. Drop every non-CPU factory before the first backend resolution.
import jax  # noqa: E402
import jax._src.xla_bridge as _xb  # noqa: E402

for _name in [n for n in _xb._backend_factories if n != "cpu"]:
    del _xb._backend_factories[_name]

# sitecustomize may have imported jax at interpreter start, freezing the
# platform config from the pre-override environment; update it explicitly.
jax.config.update("jax_platforms", "cpu")
flags = os.environ.get("XLA_FLAGS", "")
if "xla_force_host_platform_device_count" not in flags:
    os.environ["XLA_FLAGS"] = (
        flags + " --xla_force_host_platform_device_count=8"
    ).strip()
