#!/usr/bin/env python
"""Headline-scale (50k x 5k) sharded-solve stage of the multi-chip proof.

Separate from ``__graft_entry__.dryrun_multichip`` because at this scale
the four solves plus compiles take ~5-7 minutes on the 1-core CPU mesh —
too slow for the driver's dryrun budget. Run manually:

    PALLAS_AXON_POOL_IPS= JAX_PLATFORMS=cpu \
    XLA_FLAGS=--xla_force_host_platform_device_count=8 \
    python tools/multichip_50k.py --out MULTICHIP_50K_r05.json

Asserts bit-exact placement parity between the single-device staged
solver and the hierarchical sharded solver (solver/spmd.py) and records
interleaved wall times. On a 1-core host the 8 virtual devices
serialize, so the sharded number measures pure sharding overhead — the
[T, N/s] blocks sum to the same work; real ICI runs them in parallel.
"""

import argparse
import json
import os
import sys
import time

sys.path.insert(0, os.path.dirname(os.path.dirname(os.path.abspath(__file__))))


def main():
    ap = argparse.ArgumentParser()
    ap.add_argument("--out", default=None)
    ap.add_argument("--devices", type=int, default=8)
    args = ap.parse_args()

    from kube_batch_tpu.utils.backend import force_cpu_devices

    # Same hardening as __graft_entry__: drop any site-injected tunnel
    # backend factory BEFORE jax resolves a backend (a wedged tunnel
    # hangs or errors every jax call otherwise).
    if not force_cpu_devices(args.devices):
        raise SystemExit("CPU mesh unavailable (jax already initialized)")

    import jax
    import numpy as np
    from jax.sharding import Mesh

    import __graft_entry__ as g
    from kube_batch_tpu.solver import solve_staged_jit, solve_sharded

    big = g._synthetic_inputs(T=50_000, N=5_120, R=3, Q=5, J=2000, seed=2)
    mesh = Mesh(np.asarray(jax.devices()[: args.devices]), ("nodes",))

    # Warm both compiles, then interleave best-of-2 (noisy box).
    single = jax.block_until_ready(solve_staged_jit(big, max_rounds=64))
    sharded = jax.block_until_ready(
        solve_sharded(big, mesh, max_rounds=64, staged=True)
    )
    t_single, t_sharded = [], []
    for _ in range(2):
        t0 = time.perf_counter()
        single = jax.block_until_ready(solve_staged_jit(big, max_rounds=64))
        t_single.append(time.perf_counter() - t0)
        t0 = time.perf_counter()
        sharded = jax.block_until_ready(
            solve_sharded(big, mesh, max_rounds=64, staged=True)
        )
        t_sharded.append(time.perf_counter() - t0)

    a1 = np.asarray(single.assigned)
    a2 = np.asarray(sharded.assigned)
    parity = bool((a1 == a2).all())
    assert parity, f"{int((a1 != a2).sum())} rows diverge"
    out = {
        "shape": [50_000, 5_120],
        "devices": args.devices,
        "placed": int((a2 >= 0).sum()),
        "parity_with_single_device": parity,
        "rounds": int(sharded.rounds),
        "stages": int(sharded.stages),
        "single_device_staged_solve_s": round(min(t_single), 2),
        "sharded_staged_solve_s": round(min(t_sharded), 2),
        "sharded_impl": "spmd-hierarchical",
        "host_cpu_count": os.cpu_count(),
        "recorded": time.strftime("%Y-%m-%dT%H:%M:%SZ", time.gmtime()),
    }
    line = json.dumps(out)
    print(line)
    if args.out:
        with open(args.out, "w") as f:
            f.write(line + "\n")


if __name__ == "__main__":
    main()
