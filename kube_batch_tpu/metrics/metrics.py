"""Scheduling metrics.

Mirrors reference pkg/scheduler/metrics/metrics.go (:37-120 definitions,
:122-170 update helpers): e2e/action/plugin/task scheduling latency
histograms, schedule attempts, preemption counters, unschedulable gauges.
The reference exports via Prometheus under namespace "volcano"
(metrics.go:27); here a dependency-free registry with a Prometheus
text-exposition dump serves the same purpose (served by cli.server).
"""

from __future__ import annotations

import threading
from bisect import bisect_left
from typing import Dict, List, Optional, Tuple

NAMESPACE = "tpu_batch"

# Default latency buckets (seconds), log-spaced like prometheus.DefBuckets.
_DEF_BUCKETS = [
    0.0001, 0.00025, 0.0005, 0.001, 0.0025, 0.005, 0.01, 0.025, 0.05,
    0.1, 0.25, 0.5, 1.0, 2.5, 5.0, 10.0,
]

# Millisecond-scale buckets (seconds) for the cycle-shaped histograms
# (e2e / per-action / solver-phase). A steady production cycle runs
# ~10-300 ms; prometheus.DefBuckets puts exactly FOUR boundaries in
# that range (25/50/100/250 ms), so every cycle-latency quantile
# collapsed into the same handful of buckets. These give ~15%
# resolution across 1 ms - 1 s and keep a coarse multi-second tail for
# cold/degraded cycles. Bucket policy: doc/design/metrics.md.
MS_BUCKETS = [
    0.001, 0.0025, 0.005, 0.0075, 0.01, 0.015, 0.02, 0.03, 0.045,
    0.065, 0.09, 0.125, 0.175, 0.25, 0.35, 0.5, 0.75, 1.0, 2.5, 10.0,
]


class _Metric:
    def __init__(self, name: str, help_text: str):
        self.name = f"{NAMESPACE}_{name}"
        self.help = help_text
        self._lock = threading.Lock()


class Counter(_Metric):
    def __init__(self, name, help_text=""):
        super().__init__(name, help_text)
        self._values: Dict[Tuple, float] = {}

    def inc(self, labels: Tuple = (), amount: float = 1.0) -> None:
        with self._lock:
            self._values[labels] = self._values.get(labels, 0.0) + amount

    def get(self, labels: Tuple = ()) -> float:
        return self._values.get(labels, 0.0)

    def total(self) -> float:
        """Sum across every label set (engagement asserts in smokes)."""
        with self._lock:
            return sum(self._values.values())

    def remove(self, labels: Tuple) -> bool:
        """Drop one label set (label GC for deleted subjects — without
        this, per-job series accumulate forever; Prometheus clients
        call this deleteLabelValues). Returns True if it existed."""
        with self._lock:
            return self._values.pop(labels, None) is not None

    def series_count(self) -> int:
        return len(self._values)

    def expose(self, label_names: Tuple = ()) -> List[str]:
        lines = [f"# TYPE {self.name} counter"]
        for labels, v in sorted(self._values.items()):
            sel = ",".join(f'{n}="{val}"' for n, val in zip(label_names, labels))
            lines.append(f"{self.name}{{{sel}}} {v}" if sel else f"{self.name} {v}")
        return lines


class Gauge(_Metric):
    def __init__(self, name, help_text=""):
        super().__init__(name, help_text)
        self._values: Dict[Tuple, float] = {}

    def set(self, value: float, labels: Tuple = ()) -> None:
        with self._lock:
            self._values[labels] = value

    def get(self, labels: Tuple = ()) -> float:
        return self._values.get(labels, 0.0)

    def remove(self, labels: Tuple) -> bool:
        """Drop one label set (see Counter.remove)."""
        with self._lock:
            return self._values.pop(labels, None) is not None

    def series_count(self) -> int:
        return len(self._values)

    def label_sets(self) -> List[Tuple]:
        """Snapshot of live label sets (label GC sweeps diff against
        this)."""
        with self._lock:
            return list(self._values)

    def expose(self, label_names: Tuple = ()) -> List[str]:
        lines = [f"# TYPE {self.name} gauge"]
        for labels, v in sorted(self._values.items()):
            sel = ",".join(f'{n}="{val}"' for n, val in zip(label_names, labels))
            lines.append(f"{self.name}{{{sel}}} {v}" if sel else f"{self.name} {v}")
        return lines


class Histogram(_Metric):
    def __init__(self, name, help_text="", buckets: Optional[List[float]] = None):
        super().__init__(name, help_text)
        self.buckets = sorted(buckets or _DEF_BUCKETS)
        self._counts: Dict[Tuple, List[int]] = {}
        self._sums: Dict[Tuple, float] = {}
        self._totals: Dict[Tuple, int] = {}

    def observe(self, value: float, labels: Tuple = ()) -> None:
        with self._lock:
            if labels not in self._counts:
                self._counts[labels] = [0] * len(self.buckets)
            # Prometheus `le` is inclusive: value lands in the first bucket
            # with bound >= value.
            idx = bisect_left(self.buckets, value)
            for i in range(idx, len(self.buckets)):
                self._counts[labels][i] += 1
            self._sums[labels] = self._sums.get(labels, 0.0) + value
            self._totals[labels] = self._totals.get(labels, 0) + 1

    def observe_many(self, values, labels: Tuple = ()) -> None:
        """Batched :meth:`observe`: one lock hold and vectorized bucket
        math for a whole array of samples (50k per cold apply — the
        per-call Python bucket loop was measurable there)."""
        import numpy as np

        arr = np.asarray(values, dtype=np.float64)
        if arr.size == 0:
            return
        n_buckets = len(self.buckets)
        idx = np.searchsorted(self.buckets, arr, side="left")
        binc = np.bincount(idx, minlength=n_buckets + 1)
        # observe() adds 1 to every bucket >= the sample's: bucket i
        # gains the count of samples with idx <= i (cumulative counts).
        cum = np.cumsum(binc[:n_buckets])
        with self._lock:
            if labels not in self._counts:
                self._counts[labels] = [0] * n_buckets
            counts = self._counts[labels]
            for i in range(n_buckets):
                counts[i] += int(cum[i])
            self._sums[labels] = self._sums.get(labels, 0.0) + float(arr.sum())
            self._totals[labels] = self._totals.get(labels, 0) + int(arr.size)

    def count(self, labels: Tuple = ()) -> int:
        with self._lock:
            return self._totals.get(labels, 0)

    def sum(self, labels: Tuple = ()) -> float:
        with self._lock:
            return self._sums.get(labels, 0.0)

    def remove(self, labels: Tuple) -> bool:
        """Drop one label set (see Counter.remove)."""
        with self._lock:
            existed = self._totals.pop(labels, None) is not None
            self._counts.pop(labels, None)
            self._sums.pop(labels, None)
            return existed

    def series_count(self) -> int:
        with self._lock:
            return len(self._totals)

    def expose(self, label_names: Tuple = ()) -> List[str]:
        lines = [f"# TYPE {self.name} histogram"]
        # Under the lock: a scrape iterating the label maps while the
        # scheduler thread observes (or GC removes a series) is a
        # dict-changed-during-iteration crash on the HTTP worker
        # (kbtlint guarded-by bring-up).
        with self._lock:
            for labels in sorted(self._totals):
                base = ",".join(
                    f'{n}="{val}"' for n, val in zip(label_names, labels)
                )
                for b, c in zip(self.buckets, self._counts[labels]):
                    sel = f'{base},le="{b}"' if base else f'le="{b}"'
                    lines.append(f"{self.name}_bucket{{{sel}}} {c}")
                inf_sel = f'{base},le="+Inf"' if base else 'le="+Inf"'
                lines.append(
                    f"{self.name}_bucket{{{inf_sel}}} {self._totals[labels]}"
                )
                sel = f"{{{base}}}" if base else ""
                lines.append(f"{self.name}_sum{sel} {self._sums[labels]}")
                lines.append(f"{self.name}_count{sel} {self._totals[labels]}")
        return lines


class Registry:
    def __init__(self):
        self._metrics: List[Tuple[_Metric, Tuple]] = []

    def register(self, metric: _Metric, label_names: Tuple = ()):
        self._metrics.append((metric, label_names))
        return metric

    def names(self) -> List[str]:
        """Registered metric names WITHOUT the namespace prefix — the
        census drift guard (tests/unit/test_metrics_census.py) compares
        these against doc/design/metrics.md's tables."""
        prefix = f"{NAMESPACE}_"
        out = []
        for metric, _labels in self._metrics:
            name = metric.name
            if name.startswith(prefix):
                name = name[len(prefix):]
            out.append(name)
        return out

    def series_count(self) -> int:
        """Total label sets held across every registered metric — the
        cardinality watermark the soak-mode leak detector fits growth
        on (a per-job label leak shows here as a line going up)."""
        return sum(
            metric.series_count() for metric, _labels in self._metrics
        )

    def expose_text(self) -> str:
        lines: List[str] = []
        for metric, label_names in self._metrics:
            lines.extend(metric.expose(label_names))
        return "\n".join(lines) + "\n"


REGISTRY = Registry()

# Metric set mirrors reference metrics.go:37-120. The cycle-shaped
# histograms (e2e / action / solver-phase) get ms-scale buckets: a
# steady cycle is ~10-300 ms and the default log-spaced set has almost
# no resolution there (doc/design/metrics.md, bucket policy).
e2e_scheduling_latency = REGISTRY.register(
    Histogram("e2e_scheduling_latency_seconds", "E2E scheduling latency",
              buckets=MS_BUCKETS)
)
plugin_scheduling_latency = REGISTRY.register(
    Histogram("plugin_scheduling_latency_seconds", "Plugin latency"),
    ("plugin", "OnSession"),
)
action_scheduling_latency = REGISTRY.register(
    Histogram("action_scheduling_latency_seconds", "Action latency",
              buckets=MS_BUCKETS),
    ("action",),
)
task_scheduling_latency = REGISTRY.register(
    Histogram("task_scheduling_latency_seconds", "Task scheduling latency")
)
schedule_attempts = REGISTRY.register(
    Counter("schedule_attempts_total", "Scheduling attempts by result"),
    ("result",),
)
preemption_victims = REGISTRY.register(
    Gauge("pod_preemption_victims", "Number of selected preemption victims")
)
preemption_attempts = REGISTRY.register(
    Counter("total_preemption_attempts", "Total preemption attempts")
)
unschedule_task_count = REGISTRY.register(
    Gauge("unschedule_task_count", "Unschedulable tasks per job"), ("job_id",)
)
unschedule_job_count = REGISTRY.register(
    Gauge("unschedule_job_count", "Number of unschedulable jobs")
)
job_retry_count = REGISTRY.register(
    Counter("job_retry_counts", "Job retries"), ("job_id",)
)
pod_group_phase_count = REGISTRY.register(
    Gauge("pod_group_phase_count", "PodGroups per phase"), ("phase",)
)
solver_iterations = REGISTRY.register(
    Gauge("solver_iterations", "TPU solver rounds used in the last cycle")
)
solver_backend_cycles = REGISTRY.register(
    Counter(
        "solver_backend_cycles",
        "Cycles solved per backend (jax device vs native CPU fallback)",
    ),
    ("backend",),
)
solver_phase_latency = REGISTRY.register(
    Histogram(
        "solver_phase_latency_seconds",
        "allocate_tpu per-phase latency (tensorize/solve/apply/epilogue)",
        buckets=MS_BUCKETS,
    ),
    ("phase",),
)
# Incremental-snapshot + device-residency counters (PR 1's dirty-name
# ledger and PR 2's device cache): cache-hit regressions must show in
# Prometheus, not just bench JSON.
tensorize_cycles = REGISTRY.register(
    Counter(
        "tensorize_cycles_total",
        "Tensorize node-array refreshes by path (incremental vs "
        "full-rebuild reason)",
    ),
    ("path",),
)
tensorize_dirty_rows = REGISTRY.register(
    Counter(
        "tensorize_dirty_rows_total",
        "Node rows patched by incremental tensorize",
    )
)
tensorize_wave_patches = REGISTRY.register(
    Counter(
        "tensorize_wave_patches_total",
        "Node rows patched through the allocation-only (placement "
        "wave) path: idle + task-count columns only, driven by the "
        "narrow dirty ledger",
    )
)
scheduler_micro_cycles = REGISTRY.register(
    Counter(
        "scheduler_micro_cycles_total",
        "Event-driven micro cycles by outcome: solve (warm placement "
        "made), noop (nothing to place), deferred (warm plan could "
        "not engage; left to the periodic cycle)",
    ),
    ("outcome",),
)
solver_warm_starts = REGISTRY.register(
    Counter(
        "solver_warm_starts_total",
        "Warm-start plan outcomes per solving cycle: noop (previous "
        "verdicts reused bit-for-bit, solve skipped), solve (new work "
        "only, residual capacities), or the full-solve fallback reason "
        "(cold/stale/node-dirty/releasing/carried-changed/"
        "deserved-changed/drift/disabled; subset = rank-stable "
        "subset solve of carried+new work)",
    ),
    ("outcome",),
)
device_cache_rows_patched = REGISTRY.register(
    Counter(
        "device_cache_rows_patched_total",
        "Rows scatter-patched into resident device buffers",
    )
)
device_cache_bytes_shipped = REGISTRY.register(
    Counter(
        "device_cache_bytes_shipped_total",
        "Host->device bytes actually shipped by the snapshot pack",
    )
)
device_cache_fields = REGISTRY.register(
    Counter(
        "device_cache_fields_total",
        "Per-field pack outcomes (reuse / patch / upload)",
    ),
    ("outcome",),
)
device_cache_full_uploads = REGISTRY.register(
    Counter(
        "device_cache_full_uploads_total",
        "Full-buffer uploads by reason "
        "(cold/shape-change/bulk-dirty/small-buffer)",
    ),
    ("reason",),
)
solver_jit_compilations = REGISTRY.register(
    Gauge(
        "solver_jit_compilations",
        "Distinct compiled variants across the solver and patch jits "
        "(growth across steady cycles = a retrace regression)",
    )
)
# Candidate-sparsified solve counters (solver/topk.py + the sparse
# kernels/native loop): engagement, refill work, and dense fallbacks
# must be observable in Prometheus, not just bench JSON.
solver_sparse_solves = REGISTRY.register(
    Counter(
        "solver_sparse_solves_total",
        "Cycles solved through the top-K candidate-sparsified path",
    )
)
solver_sparse_refill_rounds = REGISTRY.register(
    Counter(
        "solver_sparse_refill_rounds_total",
        "Candidate refill rounds (slab exhaustion -> widened/compacted "
        "dense stages) across sparse solves",
    )
)
solver_sparse_dense_fallbacks = REGISTRY.register(
    Counter(
        "solver_sparse_dense_fallbacks_total",
        "Solves that fell back to the dense path by reason "
        "(class-budget/sharded-mesh/env-disabled/ladder-degraded)",
    ),
    ("reason",),
)
solver_sparse_slab_bytes = REGISTRY.register(
    Counter(
        "solver_sparse_slab_bytes_shipped_total",
        "Host->device bytes shipped for candidate-slab fields "
        "(cand_idx/cand_static/cand_info) by the snapshot pack",
    )
)
solver_sparse_sharded = REGISTRY.register(
    Counter(
        "solver_sparse_sharded_solves_total",
        "Cycles whose sparse solve ran sharded over the device mesh, "
        "by mode (flat = bit-parity task-sharded shard_map, two-level "
        "= per-rack solve + global reconciliation)",
    ),
    ("mode",),
)
solver_selection_device = REGISTRY.register(
    Counter(
        "solver_selection_device_total",
        "Selection passes whose per-class scoring and top-K extraction "
        "ran on the device-resident key matrix "
        "(solver/select_device.py; host fallbacks are labeled in "
        "tensorize stats, not here)",
    )
)
# Scheduling-loop robustness + simulator counters (the long-horizon
# harness in kube_batch_tpu/sim must be observable like everything
# else: a fault run that silently stops injecting, or an invariant
# violation eaten by a log filter, would void the whole exercise).
scheduler_cycle_errors = REGISTRY.register(
    Counter(
        "scheduler_cycle_errors_total",
        "Scheduling cycles that raised (caught by the guarded loop, "
        "retried with capped exponential backoff)",
    )
)
# Solver fault containment (kube_batch_tpu/solver/containment.py +
# actions/allocate_tpu.py ladder): every time a cycle's solve descends
# a rung (sparse -> dense -> native), why, plus the circuit breaker's
# state machine and the loop watchdog.
solver_fallback = REGISTRY.register(
    Counter(
        "solver_fallback_total",
        "Solve-ladder descents by rung pair and reason "
        "(exception/timeout/breaker-open/tensorize/rejected) — the "
        "fault-containment layer re-solving a cycle on a lower rung "
        "instead of failing it",
    ),
    ("from", "to", "reason"),
)
solver_breaker_state = REGISTRY.register(
    Gauge(
        "solver_breaker_state",
        "Device-path circuit breaker state (0=closed, 1=half-open, "
        "2=open); open pins cycles to the native floor until the "
        "canary probe re-promotes",
    )
)
solver_breaker_transitions = REGISTRY.register(
    Counter(
        "solver_breaker_transitions_total",
        "Circuit breaker state transitions by target state",
    ),
    ("to",),
)
scheduler_watchdog_trips = REGISTRY.register(
    Counter(
        "scheduler_watchdog_trips_total",
        "Loop-watchdog detections of a cycle exceeding its no-progress "
        "budget (flight recorder dumped, leadership fenced)",
    )
)
task_resync_terminal = REGISTRY.register(
    Counter(
        "task_resync_terminal_total",
        "Poisoned tasks dropped from the resync queue after exhausting "
        "the max reconcile attempts (named in the job's unschedulable "
        "verdict detail)",
    )
)
cache_binds_fenced = REGISTRY.register(
    Counter(
        "cache_binds_fenced_total",
        "Bind/evict side effects refused by the leadership fencing "
        "check (a deposed or watchdog-fenced leader must not mutate "
        "the cluster)",
    )
)
# Crash-tolerant failover (doc/design/robustness.md, failover section):
# the bind-intent journal's lifecycle and the successor recovery pass's
# per-task reconciliation outcomes.
bind_journal_intents = REGISTRY.register(
    Counter(
        "bind_journal_intents_total",
        "Bind-intent journal events: appended (one per dispatched "
        "batch), applied/failed (one per task as its side effect "
        "drains), resolved (records fully marked and self-pruned)",
    ),
    ("event",),
)
# Cluster-truth anti-entropy (doc/design/robustness.md, event-stream
# hardening): watch-ingest guard absorptions, gap-repair relists, the
# divergence sweep's detections/repairs, and post-solve placement
# validation rejections.
cache_event_anomalies = REGISTRY.register(
    Counter(
        "cache_event_anomalies_total",
        "Watch-event anomalies absorbed by the cache ingest guards: "
        "duplicate (same resourceVersion redelivered), stale (older "
        "than the applied version), reorder (out-of-order arrival that "
        "filled a stream hole), gap (a hole confirmed as a DROPPED "
        "event — queues a rate-limited relist)",
    ),
    ("kind",),
)
cache_relists = REGISTRY.register(
    Counter(
        "cache_relists_total",
        "Watch-gap repair relists (bounded, rate-limited full "
        "reconciles through the anti-entropy engine) by outcome",
    ),
    ("outcome",),
)
cache_divergence_detected = REGISTRY.register(
    Counter(
        "cache_divergence_detected_total",
        "Mirror-vs-cluster-truth divergences found by the anti-entropy "
        "sweep, by kind (phantom-task/missed-pod/missed-bind/"
        "stale-task/vanished-node/missed-node/stale-node)",
    ),
    ("kind",),
)
cache_divergence_repaired = REGISTRY.register(
    Counter(
        "cache_divergence_repaired_total",
        "Divergences repaired through the dirty-ledger-stamping event "
        "handlers, by kind — detected minus repaired is the deferred "
        "backlog the next sweep retries",
    ),
    ("kind",),
)
solver_output_rejected = REGISTRY.register(
    Counter(
        "solver_output_rejected_total",
        "Solver placements rejected by post-solve validation before "
        "bind dispatch, by reason (bad-index/infeasible/capacity) — a "
        "device rung whose output fails validation re-solves one rung "
        "down; the native floor drops the offending placements",
    ),
    ("reason",),
)
scheduler_failover_recoveries = REGISTRY.register(
    Counter(
        "scheduler_failover_recoveries_total",
        "Successor-recovery task reconciliations by outcome: applied "
        "(bind landed; confirmed or mark back-filled), failed (the "
        "dead leader already reverted it), redriven (lost bind "
        "re-issued to its journaled node to complete a partial gang), "
        "requeued (lost bind left to normal scheduling), evicted "
        "(partial gang below minMember torn down — all-or-nothing "
        "restored), superseded (another leader placed it elsewhere), "
        "vanished (pod gone)",
    ),
    ("outcome",),
)
sim_cycles = REGISTRY.register(
    Counter("sim_cycles_total", "Simulated scheduling cycles driven")
)
sim_faults_injected = REGISTRY.register(
    Counter(
        "sim_faults_injected_total",
        "Simulator faults injected by kind "
        "(bind/node-flap/node-death/evict/solver/crash)",
    ),
    ("kind",),
)
sim_invariant_violations = REGISTRY.register(
    Counter(
        "sim_invariant_violations_total",
        "Invariant-checker violations by invariant "
        "(oversubscribe/gang/conservation/queue-share)",
    ),
    ("invariant",),
)
# Explainability (kube_batch_tpu/obs/explain.py): unassigned pending
# tasks bucketed by the solver's last-cycle verdict, so a dashboard
# can split "pending because predicates" from "pending because gang
# threshold" without scraping /debug/jobs.
unschedulable_tasks = REGISTRY.register(
    Gauge(
        "unschedulable_tasks",
        "Unassigned pending tasks by last-cycle verdict reason "
        "(predicate-blocked/queue-overused/refill-exhausted/"
        "gang-minmember/no-fit)",
    ),
    ("reason",),
)
# Placement-latency SLI (kube_batch_tpu/obs/latency.py): per-pod
# arrival→bind latency, stage-decomposed, observed at the bind-applied
# seam. MS_BUCKETS resolution for the fast stages (the micro-path
# budget is quoted in milliseconds) PLUS a multi-minute tail: the
# queue_wait/total/gang_total stages routinely exceed 10 s under
# saturation (the soak drift bound is 120 s), and a histogram whose
# top bucket is 10 s would pin every saturated-quantile at +Inf.
LATENCY_BUCKETS = MS_BUCKETS + [30.0, 60.0, 120.0, 300.0]
pod_placement_latency = REGISTRY.register(
    Histogram(
        "pod_placement_latency_seconds",
        "Per-pod placement latency by stage (queue_wait/solve/dispatch/"
        "bind/total, plus gang_total = a gang's last-member "
        "bind-applied), queue, and the placing cycle kind "
        "(periodic/micro)",
        buckets=LATENCY_BUCKETS,
    ),
    ("stage", "queue", "cycle_kind"),
)
# Long-horizon telemetry watermarks (kube_batch_tpu/obs/telemetry.py):
# the Prometheus face of the per-cycle watermark probes the soak-mode
# leak detectors fit trends on. Gauges, updated once per cycle.
process_rss_bytes = REGISTRY.register(
    Gauge("process_rss_bytes", "Scheduler process resident set size")
)
jax_device_memory_bytes = REGISTRY.register(
    Gauge(
        "jax_device_memory_bytes",
        "Live device memory across local jax devices (0 when the "
        "backend exposes no memory_stats, e.g. CPU)",
    )
)
metrics_label_series = REGISTRY.register(
    Gauge(
        "metrics_label_series",
        "Label sets held across this registry — unbounded growth here "
        "is a label-cardinality leak (per-job series must be GC'd on "
        "job deletion)",
    )
)
telemetry_windows_rolled = REGISTRY.register(
    Gauge(
        "telemetry_windows_rolled",
        "Telemetry rollup windows closed since start",
    )
)
telemetry_ring_occupancy = REGISTRY.register(
    Gauge(
        "telemetry_ring_occupancy",
        "Per-cycle samples currently held in the telemetry raw ring",
    )
)
queue_fairness_drift = REGISTRY.register(
    Gauge(
        "queue_fairness_drift",
        "Per-queue (allocated - deserved) on the dominant dimension as "
        "a fraction of cluster capacity; sustained positive drift "
        "means a queue is being over-served",
    ),
    ("queue",),
)
# Serving SLO accounting (kube_batch_tpu/obs/latency.py serving
# extension, doc/design/serving.md): placement-latency SLO verdicts
# per workload class, observed at the bind-applied seam.
pod_slo_placements = REGISTRY.register(
    Counter(
        "pod_slo_placements_total",
        "Placements of pods carrying a placement-latency SLO target, "
        "by workload class and verdict (met = total latency within "
        "the per-job target at bind-applied)",
    ),
    ("workload_class", "outcome"),
)
serving_slo_attainment = REGISTRY.register(
    Gauge(
        "serving_slo_attainment",
        "Fraction of serving-class targeted placements that met their "
        "placement-latency SLO (cumulative; 1.0 until the first "
        "targeted placement)",
    )
)
serving_slo_budget_burn = REGISTRY.register(
    Gauge(
        "serving_slo_budget_burn",
        "Serving violation-budget burn: SLO misses divided by the "
        "misses allowed at KBT_SERVING_ATTAINMENT_TARGET (>1 = the "
        "attainment budget is blown)",
    )
)
# Placement-quality scorecard (kube_batch_tpu/obs/quality.py,
# doc/design/quality.md): the Prometheus face of the per-card quality
# signals. Gauges updated once per KBT_QUALITY_EVERY cycles; the churn
# counters tick at the cache's evict/bind seams.
quality_packing_density = REGISTRY.register(
    Gauge(
        "quality_packing_density",
        "Cluster-aggregate used/allocatable per resource dimension "
        "(the packing-density headline of the quality scorecard)",
    ),
    ("resource",),
)
quality_fairness_jain = REGISTRY.register(
    Gauge(
        "quality_fairness_jain",
        "Jain fairness index over per-queue satisfaction ratios "
        "(allocated vs water-filled deserved; 1.0 = perfectly "
        "proportional)",
    )
)
quality_emptiable_nodes = REGISTRY.register(
    Gauge(
        "quality_emptiable_nodes",
        "Nodes that are empty or could be drained into the remaining "
        "idle headroom (fragmentation/consolidation watermark)",
    )
)
quality_largest_placeable_gang = REGISTRY.register(
    Gauge(
        "quality_largest_placeable_gang",
        "Per queue: members of its largest pending gang the current "
        "idle vectors could hold (series GC'd when the queue has no "
        "pending gang)",
    ),
    ("queue",),
)
quality_churn_per_placement = REGISTRY.register(
    Gauge(
        "quality_churn_per_placement",
        "Disruption churn: (evictions + re-binds) per placement over "
        "the last scorecard interval",
    )
)
quality_evictions = REGISTRY.register(
    Counter(
        "quality_evictions_total",
        "Task evictions observed by the quality monitor, by reason "
        "(preempt/reclaim/node-death/...)",
    ),
    ("reason",),
)
quality_rebinds = REGISTRY.register(
    Counter(
        "quality_rebinds_total",
        "Re-binds: binds of tasks previously evicted (the disruption "
        "half of preemption churn actually paid back)",
    )
)


# Update helpers (reference metrics.go:122-170).

def update_e2e_duration(seconds: float) -> None:
    e2e_scheduling_latency.observe(seconds)


def update_plugin_duration(plugin: str, on_session: str, seconds: float) -> None:
    plugin_scheduling_latency.observe(seconds, (plugin, on_session))


def update_action_duration(action: str, seconds: float) -> None:
    action_scheduling_latency.observe(seconds, (action,))


def update_task_schedule_duration(seconds: float) -> None:
    task_scheduling_latency.observe(seconds)


def update_task_schedule_durations(seconds_list) -> None:
    """Batched form for the 50k-task apply path."""
    task_scheduling_latency.observe_many(seconds_list)


def update_pod_group_phase(phase: str, count: int) -> None:
    pod_group_phase_count.set(count, (phase,))


def update_preemption_victims(count: int) -> None:
    preemption_victims.set(count)


def register_preemption_attempts() -> None:
    preemption_attempts.inc()


def update_unschedulable_task_count(job_id: str, count: int) -> None:
    unschedule_task_count.set(count, (job_id,))


def update_unschedulable_job_count(count: int) -> None:
    unschedule_job_count.set(count)


def register_job_retries(job_id: str) -> None:
    job_retry_count.inc((job_id,))


def forget_job(job_id: str) -> None:
    """Label-set GC for a deleted job: drop its per-job series from
    the gauges/counters keyed on ``job_id``. Without this, every job
    that ever went unschedulable leaves an immortal series behind —
    an unbounded-cardinality leak over a production-length run (the
    soak detector watches ``metrics_label_series`` for exactly this).
    Called from the cache's job-cleanup path."""
    if not job_id:
        return
    unschedule_task_count.remove((job_id,))
    job_retry_count.remove((job_id,))


def update_solver_cycle(rounds: int, backend: str) -> None:
    """Record one allocate_tpu cycle: rounds used and which backend
    solved it ("jax-<platform>" or "native")."""
    solver_iterations.set(rounds)
    solver_backend_cycles.inc((backend,))


def update_solver_phase(phase: str, seconds: float) -> None:
    """Per-phase allocate_tpu latency (the cycle budget split the
    reference has no analog for: host tensorize vs device solve vs host
    apply)."""
    solver_phase_latency.observe(seconds, (phase,))


def update_tensorize_cycle(
    incremental: bool, dirty_rows: int, full_reason=None,
    wave_patched: int = 0,
) -> None:
    """Record one tensorize node-array refresh: which path ran and how
    many rows it actually touched."""
    path = "incremental" if incremental else f"full-{full_reason}"
    tensorize_cycles.inc((path,))
    # Only rows actually patched count; a full rebuild reports N "dirty"
    # rows but ships through the rebuild path, not the patch path.
    if incremental and dirty_rows:
        tensorize_dirty_rows.inc(amount=float(dirty_rows))
    if incremental and wave_patched:
        tensorize_wave_patches.inc(amount=float(wave_patched))


def register_warm_start(outcome: str) -> None:
    solver_warm_starts.inc((outcome,))


def register_micro_cycle(outcome: str) -> None:
    scheduler_micro_cycles.inc((outcome,))


def register_device_selection() -> None:
    """One selection pass ran on the device-resident key matrix."""
    solver_selection_device.inc()


def update_device_cache(stats: dict) -> None:
    """Fold one device-cache pack into the counters (``stats`` is
    device_cache.last_pack_stats' schema)."""
    if stats.get("rows_patched"):
        device_cache_rows_patched.inc(amount=float(stats["rows_patched"]))
    if stats.get("bytes_shipped"):
        device_cache_bytes_shipped.inc(
            amount=float(stats["bytes_shipped"])
        )
    for key, outcome in (
        ("reuses", "reuse"), ("patches", "patch"), ("uploads", "upload")
    ):
        if stats.get(key):
            device_cache_fields.inc((outcome,), amount=float(stats[key]))
    for reason in stats.get("full_reasons", {}).values():
        device_cache_full_uploads.inc((reason,))
    if stats.get("slab_bytes_shipped"):
        solver_sparse_slab_bytes.inc(
            amount=float(stats["slab_bytes_shipped"])
        )


# Dense-fallback reasons that represent a genuine fallback (the sparse
# path was wanted but could not run), as opposed to the size policy
# simply preferring dense on a small problem.
_SPARSE_FALLBACK_REASONS = frozenset(
    ("class-budget", "sharded-mesh", "env-disabled", "ladder-degraded")
)


def update_solver_sparse(
    engaged: bool, refill_rounds: int, fallback_reason=None
) -> None:
    """Record one allocate_tpu solve's sparse-path outcome."""
    if engaged:
        solver_sparse_solves.inc()
        if refill_rounds:
            solver_sparse_refill_rounds.inc(amount=float(refill_rounds))
    elif fallback_reason in _SPARSE_FALLBACK_REASONS:
        solver_sparse_dense_fallbacks.inc((fallback_reason,))


def register_sparse_sharded(mode: str) -> None:
    """One cycle's sparse solve ran sharded over the mesh (mode =
    flat | two-level, solver/sharding.sparse_shard_mode)."""
    solver_sparse_sharded.inc((mode or "unknown",))


def update_solver_jit_cache(count: int) -> None:
    """Gauge of compiled solver/patch variants (retrace forensics)."""
    solver_jit_compilations.set(float(count))


def register_cycle_error() -> None:
    """One scheduling cycle raised and was absorbed by the guarded loop."""
    scheduler_cycle_errors.inc()


def register_solver_fallback(frm: str, to: str, reason: str) -> None:
    """One solve-ladder descent: the ``frm`` rung failed (``reason`` in
    exception/timeout/breaker-open) and the cycle re-solved on ``to``."""
    solver_fallback.inc((frm, to, reason))


_BREAKER_STATE_VALUES = {"closed": 0.0, "half-open": 1.0, "open": 2.0}


def update_breaker_state(state: str, transition: bool = True) -> None:
    solver_breaker_state.set(_BREAKER_STATE_VALUES.get(state, -1.0))
    if transition:
        solver_breaker_transitions.inc((state,))


def register_watchdog_trip() -> None:
    scheduler_watchdog_trips.inc()


def register_resync_terminal() -> None:
    task_resync_terminal.inc()


def register_bind_fenced() -> None:
    cache_binds_fenced.inc()


def observe_placement_latency(
    stage: str, queue: str, cycle_kind: str, seconds: float
) -> None:
    """One pod's stage latency sample, observed by the placement
    ledger at bind-applied (obs/latency.py)."""
    pod_placement_latency.observe(seconds, (stage, queue, cycle_kind))


def update_unschedulable_reasons(counts: dict) -> None:
    """Per-cycle unschedulable-task counts by verdict reason. Absent
    reasons are zeroed so the gauge never carries a stale bucket."""
    from ..obs.explain import ALL_REASONS

    for reason in ALL_REASONS:
        unschedulable_tasks.set(float(counts.get(reason, 0)), (reason,))
    for reason in counts:
        if reason not in ALL_REASONS:  # defensive: unknown classifier
            unschedulable_tasks.set(float(counts[reason]), (reason,))


def update_telemetry_watermarks(
    values: dict, raw_occupancy: int = 0, windows_rolled: int = 0,
    fairness_ran: bool = False,
) -> None:
    """Push one telemetry cycle's watermark probes to the gauges
    (obs/telemetry.py feeds this once per scheduling cycle)."""
    rss = values.get("rss_bytes")
    if rss is not None:
        process_rss_bytes.set(float(rss))
    jax_device_memory_bytes.set(
        float(values.get("jax_device_memory_bytes", 0.0))
    )
    series = values.get("metrics_series")
    if series is not None:
        metrics_label_series.set(float(series))
    telemetry_windows_rolled.set(float(windows_rolled))
    telemetry_ring_occupancy.set(float(raw_occupancy))
    fairness = {
        key.split(":", 1)[1]: float(v)
        for key, v in values.items()
        if key.startswith("fairness_drift:")
    }
    if fairness_ran:
        # The amortized probe reports every live queue at once, so a
        # gauge series outside the incoming set belongs to a deleted
        # queue — drop it (same label-GC contract as forget_job: a
        # stale {queue=...} series is exactly the cardinality-leak
        # shape the soak detector fits growth on). Gated on the probe
        # having RUN, not on a non-empty result: an empty dict (fewer
        # than two live queues) means every existing series is stale.
        for labels in queue_fairness_drift.label_sets():
            if labels and labels[0] not in fairness:
                queue_fairness_drift.remove(labels)
        for queue, v in fairness.items():
            queue_fairness_drift.set(v, (queue,))


def register_journal_event(event: str) -> None:
    """One bind-intent journal lifecycle event (cache/cache.py)."""
    bind_journal_intents.inc((event,))


def register_event_anomaly(kind: str, n: int = 1) -> None:
    """``n`` absorbed watch-event anomalies of ``kind`` (cache ingest
    guards, cache/cache.py _admit_event)."""
    if n:
        cache_event_anomalies.inc((kind,), amount=float(n))


def register_relist(outcome: str) -> None:
    """One watch-gap repair relist attempt (cache/cache.py)."""
    cache_relists.inc((outcome,))


def register_divergence(event: str, kind: str, n: int = 1) -> None:
    """``n`` anti-entropy divergences of ``kind``; ``event`` is
    detected|repaired (cache/antientropy.py)."""
    if not n:
        return
    if event == "detected":
        cache_divergence_detected.inc((kind,), amount=float(n))
    else:
        cache_divergence_repaired.inc((kind,), amount=float(n))


def register_solver_output_rejected(reason: str, n: int = 1) -> None:
    """``n`` solver placements rejected by post-solve validation
    (solver/validate.py via the allocate_tpu ladder)."""
    if n:
        solver_output_rejected.inc((reason,), amount=float(n))


def register_failover_recovery(outcome: str, count: int = 1) -> None:
    """``count`` task reconciliations with ``outcome`` from one
    successor recovery pass (cache/recovery.py)."""
    if count:
        scheduler_failover_recoveries.inc((outcome,), amount=float(count))


def register_quality_eviction(reason: str) -> None:
    """One eviction seen by the quality monitor (obs/quality.py)."""
    quality_evictions.inc((reason,))


def register_quality_rebinds(n: int) -> None:
    """``n`` binds of previously-evicted tasks (obs/quality.py)."""
    if n:
        quality_rebinds.inc(amount=float(n))


def update_quality(card: dict) -> None:
    """Push one quality scorecard to the gauges (obs/quality.py feeds
    this every KBT_QUALITY_EVERY cycles)."""
    for dim, v in card.get("density", {}).items():
        quality_packing_density.set(float(v), (dim,))
    fairness = card.get("fairness", {})
    quality_fairness_jain.set(float(fairness.get("jain", 1.0)))
    frag = card.get("frag", {})
    quality_emptiable_nodes.set(float(frag.get("emptiable_nodes", 0)))
    quality_churn_per_placement.set(
        float(card.get("churn", {}).get("per_placement", 0.0))
    )
    # Every card reports every queue with a pending gang at once, so a
    # gauge series outside the incoming set is stale — drop it (same
    # label-GC contract as queue_fairness_drift).
    gangs = frag.get("largest_gang", {})
    for labels in quality_largest_placeable_gang.label_sets():
        if labels and labels[0] not in gangs:
            quality_largest_placeable_gang.remove(labels)
    for queue, v in gangs.items():
        quality_largest_placeable_gang.set(float(v), (queue,))


def register_sim_cycle() -> None:
    sim_cycles.inc()


def register_sim_fault(kind: str) -> None:
    sim_faults_injected.inc((kind,))


def register_sim_violation(invariant: str) -> None:
    sim_invariant_violations.inc((invariant,))
