"""Multi-scheduler failover kill drills (sim/failover.py +
harness failover flow): at every cut point the successor takes the
lease, recovers from the bind-intent journal, and the invariant
checker holds across the boundary; the whole drill — including the
recovery outcome — replays bit-identically."""

import json

import pytest

from kube_batch_tpu.api.objects import GROUP_NAME_ANNOTATION_KEY
from kube_batch_tpu.sim import SimConfig, TraceReader, WorkloadSpec
from kube_batch_tpu.sim.failover import CUT_POINTS
from kube_batch_tpu.sim.harness import ClusterSimulator, run_sim


def drill_config(**kw):
    kw.setdefault("workload", WorkloadSpec(nodes=10, arrival_rate=2.0))
    kw.setdefault("backend", "native")
    kw.setdefault("cycles", 16)
    kw.setdefault("seed", 7)
    return SimConfig(**kw)


def assert_no_partial_gangs(cluster):
    """Drill-end acceptance: no gang sits strictly between 0 bound
    members and its minMember (cluster truth, first principles)."""
    from kube_batch_tpu.api import PodPhase

    min_member = {
        f"{pg.namespace}/{pg.name}": pg.spec.min_member
        for pg in cluster.list_objects("PodGroup")
    }
    bound = {}
    for pod in cluster.list_objects("Pod"):
        if not pod.spec.node_name or pod.status.phase in (
            PodPhase.SUCCEEDED, PodPhase.FAILED
        ):
            continue
        group = pod.metadata.annotations.get(GROUP_NAME_ANNOTATION_KEY)
        if group:
            key = f"{pod.namespace}/{group}"
            bound[key] = bound.get(key, 0) + 1
    for key, count in sorted(bound.items()):
        minm = min_member.get(key, 0)
        if minm > 1:
            assert count >= minm, (
                f"gang {key} left partial: {count} of {minm} bound"
            )


class TestKillDrill:
    @pytest.mark.parametrize("cut", CUT_POINTS)
    def test_each_cut_point_recovers_clean(self, cut):
        sim = ClusterSimulator(drill_config(kill_plan={6: cut}))
        report = sim.run()
        assert report.violations == []
        assert report.cycle_errors == 0
        assert report.leader_kills == 1
        assert report.recovery_failures == 0
        fo = report.failovers[0]
        assert fo["cut"] == cut
        assert fo["cycle"] == 6
        assert fo["killed"] == "sim-leader-0"
        assert fo["successor"] == "sim-leader-1"
        # The killed leader never released: the successor waited out
        # the virtual lease TTL.
        assert fo["takeover_wait_s"] > 0
        # Nothing half-satisfied at drill end, and the journal holds no
        # unreconciled predecessor intents.
        assert_no_partial_gangs(sim.cluster)
        assert sim.cluster.list_bind_intents() == []
        lease = sim.cluster.read_lease("sim", "leader")
        assert lease["holder"] == "sim-leader-1"

    def test_cut_semantics_differ_as_designed(self):
        """pre-solve dies before dispatch (no intents, no binds);
        post-solve dies after the journal append (intents, no binds);
        the successor classifies accordingly."""
        pre = ClusterSimulator(drill_config(kill_plan={6: "pre-solve"}))
        r_pre = pre.run()
        post = ClusterSimulator(
            drill_config(kill_plan={6: "post-solve-pre-drain"})
        )
        r_post = post.run()
        assert r_pre.failovers[0]["recovery"].get(
            "intents_scanned", 0
        ) == 0
        assert r_post.failovers[0]["recovery"]["intents_scanned"] >= 1
        assert r_post.failovers[0]["recovery"]["outcomes"].get(
            "requeued", 0
        ) >= 1

    def test_mid_bind_drain_repairs_gangs_by_redrive(self):
        """Pinned seed whose kill cycles leave partial gangs: the
        half-applied batches classify applied + lost, and recovery
        completes the gangs on their journaled nodes."""
        report, _ = run_sim(drill_config(
            cycles=24, seed=5,
            workload=WorkloadSpec(nodes=10, arrival_rate=3.0),
            kill_plan={6: "mid-bind-drain", 14: "mid-bind-drain"},
        ))
        assert report.violations == []
        assert report.leader_kills == 2
        outcomes = {}
        for fo in report.failovers:
            for k, v in fo["recovery"].get("outcomes", {}).items():
                outcomes[k] = outcomes.get(k, 0) + v
        assert outcomes.get("applied", 0) >= 1   # landed subset
        assert outcomes.get("redriven", 0) >= 1  # gang completed
        assert report.failovers[0]["marks_dropped"] >= 1
        # Repeated failovers: successor of the successor.
        assert report.failovers[1]["killed"] == "sim-leader-1"
        assert report.failovers[1]["successor"] == "sim-leader-2"

    def test_probabilistic_leader_kill_fault_kind(self):
        report, _ = run_sim(drill_config(
            cycles=40, seed=11, faults="leader-kill:0.1,bind:0.03",
        ))
        assert report.fault_counts.get("leader-kill", 0) >= 1
        assert report.leader_kills == report.fault_counts["leader-kill"]
        assert report.violations == []
        assert report.recovery_failures == 0

    def test_scheduling_continues_after_failover(self):
        """The successor is a fully working leader: placements keep
        landing after the kill."""
        report, trace = run_sim(drill_config(
            cycles=20, kill_plan={6: "post-solve-pre-drain"},
        ))
        after = sum(
            len(rec.get("placements", []))
            for rec in trace
            if rec.get("type") == "cycle" and rec["cycle"] > 6
        )
        assert after > 0
        assert report.violations == []


class TestDrillReplay:
    def test_drill_replays_bit_identically(self, tmp_path):
        trace_path = tmp_path / "drill.jsonl"
        cfg = drill_config(
            cycles=24, seed=13,
            workload=WorkloadSpec(nodes=10, arrival_rate=3.0),
            faults="bind:0.03",
            kill_plan={
                4: "pre-solve", 10: "post-solve-pre-drain",
                16: "mid-bind-drain", 21: "mid-close",
            },
            trace_path=str(trace_path),
        )
        report, records = run_sim(cfg)
        assert report.violations == []
        assert report.leader_kills == 4
        assert {f["cut"] for f in report.failovers} == set(CUT_POINTS)

        replay_report, replay_records = run_sim(SimConfig(
            replay=TraceReader.load(str(trace_path)),
            backend="native",
        ))
        assert replay_report.replay_mismatches == []
        assert replay_report.violations == []
        # Byte-for-byte: every cycle record, INCLUDING the failover
        # blocks (cut, takeover wait, recovery outcomes), is identical.
        rec_cycles = [r for r in records if r.get("type") == "cycle"]
        rep_cycles = [
            r for r in replay_records if r.get("type") == "cycle"
        ]
        assert json.dumps(rec_cycles, sort_keys=True) == json.dumps(
            rep_cycles, sort_keys=True
        )
        assert replay_report.leader_kills == 4

    def test_failover_divergence_is_flagged(self, tmp_path):
        """A tampered recovery outcome in the recording must read as
        replay divergence — the failover block is part of the verified
        contract, not decoration."""
        trace_path = tmp_path / "drill.jsonl"
        report, _ = run_sim(drill_config(
            cycles=12, kill_plan={6: "post-solve-pre-drain"},
            trace_path=str(trace_path),
        ))
        assert report.leader_kills == 1
        lines = trace_path.read_text().splitlines()
        out = []
        for line in lines:
            rec = json.loads(line)
            if rec.get("failover"):
                rec["failover"]["binds_refused"] += 1
            out.append(json.dumps(rec, sort_keys=True))
        trace_path.write_text("\n".join(out) + "\n")
        replay_report, _ = run_sim(SimConfig(
            replay=TraceReader.load(str(trace_path)), backend="native",
        ))
        assert 6 in replay_report.replay_mismatches
