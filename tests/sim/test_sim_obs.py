"""Simulator observability: flight-recorder dumps alongside the JSONL
trace on induced cycle errors, and --trace-out Chrome trace export with
virtual-time-stamped spans.
"""

import json
import os

from kube_batch_tpu.sim import SimConfig, WorkloadSpec
from kube_batch_tpu.sim.harness import run_sim


def _cfg(tmp_path, **kw):
    base = dict(
        cycles=12,
        seed=1,
        workload=WorkloadSpec(nodes=12),
        backend="auto",
    )
    base.update(kw)
    return SimConfig(**base)


def test_cycle_error_writes_flight_dump_with_failing_phase(tmp_path):
    trace = str(tmp_path / "run.jsonl")
    report, _records = run_sim(_cfg(
        tmp_path, faults="crash:0.4", trace_path=trace,
    ))
    assert report.cycle_errors > 0
    assert report.flight_dumps, "no flight dump recorded"
    path = report.flight_dumps[0]
    assert os.path.exists(path)
    assert path.startswith(trace)  # alongside the JSONL trace
    with open(path) as f:
        dump = json.load(f)
    assert dump["reason"] == "sim-cycle-error"
    last = dump["records"][-1]
    assert last["ok"] is False
    # The failing phase is the injected crash action, and the record
    # carries the traceback of the absorbed exception.
    assert last["phase"] == "action:sim-crash"
    assert "injected scheduler-cycle crash" in last["error"]
    assert any(
        "SimBindFailure" in line for line in last["traceback"]
    )


def test_clean_run_writes_no_flight_dump(tmp_path):
    trace = str(tmp_path / "clean.jsonl")
    report, _records = run_sim(_cfg(tmp_path, trace_path=trace))
    assert report.cycle_errors == 0
    assert not report.violations
    assert report.flight_dumps == []


def test_trace_out_exports_virtual_time_spans(tmp_path):
    out = str(tmp_path / "sim.trace.json")
    report, _records = run_sim(_cfg(tmp_path, trace_out=out))
    assert report.trace_out == out
    with open(out) as f:
        doc = json.load(f)
    spans = [e for e in doc["traceEvents"] if e.get("ph") == "X"]
    names = {e["name"] for e in spans}
    # The full cycle taxonomy shows up...
    assert {"cycle", "open_session", "close_session"} <= names
    assert "action:allocate_tpu" in names
    # ...and every span is stamped with the virtual clock.
    assert all("vtime" in e["args"] for e in spans)
    cycles = {e["args"]["cycle"] for e in spans if e["name"] == "cycle"}
    assert len(cycles) == 12
    # Tracer is disarmed after the run (no leak into later tests).
    from kube_batch_tpu.obs.tracer import TRACER

    assert not TRACER.enabled
