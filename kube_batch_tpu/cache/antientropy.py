"""Anti-entropy: periodic cluster-truth reconciliation of the mirror.

PR 7 contained solver faults and the failover work contained process
death, but the cache still TRUSTED its event stream: a lost, duplicated,
or reordered watch event silently corrupts every later warm solve. The
ingest guards (cache.py `_admit_event`) absorb what they can see; this
module is the backstop for what they cannot — divergence that already
happened. A periodic, budget-bounded sweep fingerprints the mirror
against cluster truth in hash buckets, classifies every divergence, and
repairs it through the ordinary event-handler entry points, so every
repair stamps the dirty ledger (warm-solve exactness, PR 8) and the
mirror converges without a restart. The reference kube-batch leans on
informer relist for this; a production system needs the divergence
*detected, classified and counted*, not silently papered over.

Mechanics:

- Per object (accepted pods → the union of all mirror tasks; nodes), a
  **canonical state string** captures exactly the solver-relevant
  truth: identity, placement (node), status class, resource request —
  for nodes: allocatable + readiness. Both sides canonicalize through
  the SAME code path (truth pods via ``TaskInfo(pod)``), so equality is
  by construction when consistent.
- blake2b(canonical) digests are cached per object keyed on a cheap
  version witness (mirror: ``JobInfo._ver`` / ``NodeInfo._ver``; truth:
  the cluster's per-write ``resource_version``) — a steady-state sweep
  re-hashes only objects that actually changed.
- Digests XOR-fold into ``KBT_ANTIENTROPY_BUCKETS`` buckets keyed on a
  pure identity hash, so the detailed diff walks only mismatched
  buckets: O(changed buckets) steady-state.
- Mirror tasks whose status is scheduler-internal/in-flight (ALLOCATED,
  BINDING, RELEASING, PIPELINED — a side effect is on the wire) are
  EXEMPT on both sides: the journal/resync own them, and judging them
  against truth mid-flight would "repair" perfectly healthy binds.

Divergence kinds and repairs (all through stamping entry points):

| kind | meaning | repair |
|---|---|---|
| ``phantom-task``  | mirror task, no cluster pod | ``_sync_task`` → delete |
| ``missed-pod``    | unbound cluster pod the mirror never saw | ``add_pod`` |
| ``missed-bind``   | cluster pod bound, mirror thinks unbound/absent | ``add_pod`` / ``_sync_task`` |
| ``stale-task``    | both present, state differs | ``_sync_task`` → update |
| ``vanished-node`` | mirror node, no cluster node | ``delete_node`` |
| ``missed-node``   | cluster node the mirror never saw | ``add_node`` |
| ``stale-node``    | capacity/readiness drifted | ``update_node`` |

``full_reconcile()`` is the same engine with the cadence and repair
budget waived — it is the watch-gap relist the ingest guards trigger
through the ``drain_resync_queue`` seam (cache.py `_maybe_relist`).
"""

from __future__ import annotations

import hashlib
import logging
import os
from typing import Dict, List, Optional, Tuple

from ..api import TaskInfo
from ..api.types import TaskStatus
from ..cluster.errors import retry_transient
from ..utils.lockdebug import wrap_lock

logger = logging.getLogger(__name__)

# Mirror statuses with a side effect (or session decision) in flight:
# truth legitimately disagrees until it drains, so both sides skip
# these uids for the sweep.
_INFLIGHT = frozenset({
    TaskStatus.ALLOCATED, TaskStatus.BINDING,
    TaskStatus.RELEASING, TaskStatus.PIPELINED,
})

DIVERGENCE_KINDS = (
    "phantom-task", "missed-pod", "missed-bind", "stale-task",
    "vanished-node", "missed-node", "stale-node",
)


def _res_key(r) -> str:
    sr = r.scalar_resources
    scalars = (
        ",".join(f"{k}={sr[k]:.3f}" for k in sorted(sr)) if sr else ""
    )
    return f"{r.milli_cpu:.3f}/{r.memory:.1f}/{scalars}"


def _task_canonical(ti) -> Optional[str]:
    """Solver-relevant canonical state of one task/pod, or None when
    the task is outside the sweep's jurisdiction. Truth pods and
    mirror tasks both flow through this — equality by construction
    when consistent. Outside jurisdiction: in-flight statuses (a side
    effect is on the wire; the journal/resync own them) and TERMINATED
    ones — the job-cleanup queue legitimately forgets terminated jobs
    while their pods still exist in the cluster, and judging that
    asymmetry would make the sweep re-add what cleanup just removed,
    forever."""
    status = ti.status
    if status in _INFLIGHT:
        return None
    if status == TaskStatus.PENDING:
        cls, node = "p", ""
    elif status in (
        TaskStatus.SUCCEEDED, TaskStatus.FAILED, TaskStatus.UNKNOWN
    ):
        return None
    else:  # BOUND / RUNNING — truth-visible placement
        cls, node = "r", ti.node_name or ""
    return f"{ti.uid}|{cls}|{node}|{_res_key(ti.resreq)}"


def _digest(canonical: str) -> int:
    return int.from_bytes(
        hashlib.blake2b(canonical.encode(), digest_size=8).digest(), "big"
    )


def _bucket_of(key: str, buckets: int) -> int:
    return int.from_bytes(
        hashlib.blake2b(key.encode(), digest_size=4).digest(), "big"
    ) % buckets


class AntiEntropy:
    """One cache's cluster-truth reconciler. Sweeps are serialized on
    an internal lock: in production the periodic sweep runs on the
    scheduling loop while the gap-repair relist runs on the cache's
    resync daemon thread (its idle beat), and the fingerprint caches +
    incremental XOR folds must never see an interleaved pair of
    read-modify-write passes — a torn fold would read as permanent
    phantom divergence. The lock is held across the WHOLE sweep
    (listing included): reconciles are rare and idempotent, and a
    relist waiting out a periodic sweep is strictly cheaper than
    corrupting the folds. Lock order: the sweep lock is taken BEFORE
    cache.mutex (the mirror pass and every repair acquire the mutex
    inside); nothing acquires the sweep lock while holding the mutex."""

    def __init__(self, cache):
        self.cache = cache
        self._sweep_lock = wrap_lock("cache.antientropy")
        # Process-constant configuration (census: configuration.md).
        self.enabled = os.environ.get("KBT_ANTIENTROPY", "1") != "0"
        try:
            self.every = max(
                1, int(os.environ.get("KBT_ANTIENTROPY_EVERY", "256"))
            )
        except ValueError:
            self.every = 256
        try:
            self.buckets = max(
                1, int(os.environ.get("KBT_ANTIENTROPY_BUCKETS", "64"))
            )
        except ValueError:
            self.buckets = 64
        try:
            self.budget = max(
                1, int(os.environ.get("KBT_ANTIENTROPY_BUDGET", "256"))
            )
        except ValueError:
            self.budget = 256
        self._calls = 0
        # Digest caches keyed on cheap version witnesses, with
        # INCREMENTALLY maintained per-bucket XOR folds alongside —
        # a steady-state sweep re-hashes only changed objects and the
        # bucket compare is 4×B integer equality checks, never an
        # O(objects) Python fold.
        # truth pods: uid -> (rv, digest, bucket, canonical)
        self._truth_pod_fp: Dict[str, tuple] = {}
        # truth nodes: name -> (rv, digest, bucket, canonical)
        self._truth_node_fp: Dict[str, tuple] = {}
        # mirror jobs: job_key -> (id(job), ver,
        #     {uid: (digest, bucket, canonical)}, exempt_uids,
        #     {bucket: xor-of-digests})
        self._mirror_job_fp: Dict[str, tuple] = {}
        # mirror nodes: name -> (id(ni), ver, digest, bucket, canonical)
        self._mirror_node_fp: Dict[str, tuple] = {}
        self._fold_truth_pods = [0] * self.buckets
        self._fold_truth_nodes = [0] * self.buckets
        self._fold_mirror_pods = [0] * self.buckets
        self._fold_mirror_nodes = [0] * self.buckets
        # Cumulative counters (integrity_state / sim report).
        self.detected: Dict[str, int] = {}
        self.repaired: Dict[str, int] = {}
        self.sweeps = 0
        self.last_sweep: dict = {}
        # Truth-side shortcut witnesses: when the cluster's monotone
        # event rv hasn't moved since the last sweep (and the exempt
        # set is unchanged), truth provably didn't change — the listing
        # and the O(pods) loop are skipped wholesale.
        self._last_truth_rv: Optional[int] = None
        self._last_exempt: frozenset = frozenset()

    # -- public entry points -------------------------------------------------

    def sweep_if_due(self) -> Optional[dict]:
        """Cadence gate for the periodic sweep: every
        ``KBT_ANTIENTROPY_EVERY``-th call (the scheduler calls once per
        periodic cycle) runs a budget-bounded sweep."""
        if not self.enabled:
            return None
        self._calls += 1
        if (self._calls - 1) % self.every:
            return None
        return self.sweep(budget=self.budget)

    def full_reconcile(self) -> dict:
        """The watch-gap relist: one unbudgeted sweep. Raises on a list
        failure (after the typed retry ladder) — the caller keeps the
        gap pending."""
        return self.sweep(budget=None, adopt_rvs=True)

    # -- the sweep -----------------------------------------------------------

    def sweep(self, budget: Optional[int] = None,
              adopt_rvs: bool = False) -> dict:
        """Fingerprint mirror vs truth, diff mismatched buckets, repair
        up to ``budget`` divergences (None = all). Returns the sweep
        report; raises only when the truth listing itself fails.
        Serialized on the sweep lock (see class docstring)."""
        with self._sweep_lock:
            return self._sweep_locked(budget, adopt_rvs)

    def _sweep_locked(self, budget: Optional[int],
                      adopt_rvs: bool) -> dict:
        cache = self.cache
        cluster = cache.cluster

        # Mirror canonical maps + exempt uids, under the mutex (cheap:
        # version-witnessed digest reuse; no cluster I/O inside).
        with cache.mutex:
            mirror_jobs, exempt, terminated = self._mirror_pod_fps()
            mirror_nodes = self._mirror_node_fps()

        # Truth-side shortcut: the cluster's monotone event rv is a
        # whole-world version witness — unmoved rv (and an unchanged
        # exempt set, which filters the truth maps) means the previous
        # truth fingerprints are exact, no list, no O(pods) loop. Never
        # taken on a relist (adopt_rvs): a gap means the STREAM lied,
        # so the reconcile must re-read ground truth regardless.
        cur_rv_fn = getattr(cluster, "current_resource_version", None)
        truth_rv: Optional[int] = None
        if cur_rv_fn is not None:
            try:
                truth_rv = int(cur_rv_fn())
            except Exception:  # pragma: no cover - defensive
                truth_rv = None
        exempt_frozen = frozenset(exempt)

        def read_truth() -> tuple:
            # Truth listing through the relist seam (typed retry; the
            # sim's relist-fail fault injects TransientClusterError
            # here).
            pods = retry_transient(
                lambda: cluster.list_for_relist("Pod"),
                salt="antientropy/pods",
            )
            nodes = retry_transient(
                lambda: cluster.list_for_relist("Node"),
                salt="antientropy/nodes",
            )
            pod_map = self._truth_pod_fps(pods, exempt)
            node_map = self._truth_node_fps(nodes)
            self._last_truth_rv = truth_rv
            self._last_exempt = exempt_frozen
            return pods, nodes, pod_map, node_map

        def bucket_diff() -> set:
            return {
                b for b in range(self.buckets)
                if self._fold_mirror_pods[b] != self._fold_truth_pods[b]
                or self._fold_mirror_nodes[b]
                != self._fold_truth_nodes[b]
            }

        used_shortcut = (
            not adopt_rvs
            and truth_rv is not None
            and truth_rv == self._last_truth_rv
            and exempt_frozen == self._last_exempt
        )
        if used_shortcut:
            truth_pods: list = []
            truth_nodes: list = []
            truth_pod_map = self._truth_pod_fp
            truth_node_map = self._truth_node_fp
        else:
            truth_pods, truth_nodes, truth_pod_map, truth_node_map = (
                read_truth()
            )

        # Bucket compare on the incrementally maintained folds: 2×B
        # integer checks; the detailed diff walks only disagreeing
        # buckets (empty on every consistent sweep).
        dirty = bucket_diff()
        if dirty and used_shortcut:
            # The mirror diverged without any cluster write landing (a
            # direct poke, or repair fallout): re-read ground truth
            # before judging — repairs need the live objects.
            used_shortcut = False
            truth_pods, truth_nodes, truth_pod_map, truth_node_map = (
                read_truth()
            )
            dirty = bucket_diff()

        divergences: List[Tuple[str, str, str]] = []
        if dirty:
            divergences = self._diff(
                dirty, mirror_jobs, truth_pod_map,
                mirror_nodes, truth_node_map,
            )
        if (terminated or exempt) and not used_shortcut:
            # Terminated and in-flight tasks live outside the fold, but
            # one whose cluster pod is GONE is a phantom the
            # conservation invariant flags: a terminated orphan is
            # cleanup debris, and a BINDING/RELEASING task with no pod
            # cannot be "in flight" — its bind confirm AND its delete
            # were both lost (the storm's double-drop class), so the
            # exemption must not shield it forever. (Under the rv
            # shortcut there was no listing, and no cluster delete can
            # have happened without moving the rv.)
            truth_uids = {p.metadata.uid for p in truth_pods}
            for uid in sorted((terminated | exempt) - truth_uids):
                divergences.append(("phantom-task", uid, uid))

        report = {
            "pods": len(truth_pod_map),
            "nodes": len(truth_node_map),
            "buckets_dirty": len(dirty),
            "exempt_inflight": len(exempt),
            "detected": {},
            "repaired": {},
            "deferred": 0,
        }
        for kind, _subj, _key in divergences:
            report["detected"][kind] = report["detected"].get(kind, 0) + 1
            self.detected[kind] = self.detected.get(kind, 0) + 1

        repaired_n = 0
        truth_pod_by_uid = {
            p.uid: p for p in truth_pods if p.uid in truth_pod_map
        }
        truth_node_by_name = {n.name: n for n in truth_nodes}
        for kind, subject, _key in divergences:
            if budget is not None and repaired_n >= budget:
                report["deferred"] += 1
                continue
            if self._repair(
                kind, subject, truth_pod_by_uid, truth_node_by_name,
                adopt_rvs,
            ):
                repaired_n += 1
                report["repaired"][kind] = (
                    report["repaired"].get(kind, 0) + 1
                )
                self.repaired[kind] = self.repaired.get(kind, 0) + 1
        if adopt_rvs:
            # Relist semantics: the listed versions ARE the guard
            # baseline now — late stale events predating the list must
            # be absorbed, not re-applied.
            for pod in truth_pods:
                cache._adopt_listed_rv("Pod", pod)
            for node in truth_nodes:
                cache._adopt_listed_rv("Node", node)

        self.sweeps += 1
        self.last_sweep = report
        self._export(report)
        return report

    # -- canonical fingerprint maps ------------------------------------------

    def _mirror_pod_fps(self):
        """Per-job fingerprint entries over every mirror task, plus the
        exempt (in-flight) uid set and the TERMINATED uid set;
        maintains the mirror-pod bucket fold incrementally. Caller
        holds cache.mutex. Per-JOB memoization on (identity, _ver): an
        untouched job contributes nothing but two comparisons.

        Terminated tasks live outside the fold (see _task_canonical)
        but are collected separately: one whose cluster pod is GONE is
        a phantom the conservation invariant would flag forever, so the
        sweep still repairs exactly that case (sweep() checks the set
        against the listed truth uids)."""
        exempt: set = set()
        terminated: set = set()
        fresh: Dict[str, tuple] = {}
        old = self._mirror_job_fp
        folds = self._fold_mirror_pods
        B = self.buckets
        for job_key, job in self.cache.jobs.items():
            entry = old.get(job_key)
            if (
                entry is not None
                and entry[0] == id(job)
                and entry[1] == job._ver
            ):
                fresh[job_key] = entry
                if entry[3]:
                    exempt.update(entry[3])
                if entry[5]:
                    terminated.update(entry[5])
                continue
            fps: Dict[str, tuple] = {}
            job_exempt: set = set()
            job_term: set = set()
            jfold: Dict[int, int] = {}
            for uid, task in job.tasks.items():
                canonical = _task_canonical(task)
                if canonical is None:
                    if task.status in (
                        TaskStatus.SUCCEEDED, TaskStatus.FAILED
                    ):
                        job_term.add(uid)
                    else:
                        job_exempt.add(uid)
                    continue
                d = _digest(canonical)
                b = _bucket_of(uid, B)
                fps[uid] = (d, b, canonical)
                jfold[b] = jfold.get(b, 0) ^ d
            fresh[job_key] = (
                id(job), job._ver, fps, job_exempt, jfold, job_term
            )
            if entry is not None:
                for b, x in entry[4].items():
                    folds[b] ^= x
            for b, x in jfold.items():
                folds[b] ^= x
            if job_exempt:
                exempt.update(job_exempt)
            if job_term:
                terminated.update(job_term)
        for job_key in old.keys() - fresh.keys():
            for b, x in old[job_key][4].items():
                folds[b] ^= x
        self._mirror_job_fp = fresh  # deleted jobs fall away
        return fresh, exempt, terminated

    def _mirror_node_fps(self):
        """{name: (id, ver, digest, bucket, canonical)} over mirror
        nodes, fold maintained incrementally. Caller holds cache.mutex.
        Placeholder entries (``node is None``, minted for pods naming
        an unlisted node) canonicalize as placeholders — truth either
        fills them (stale-node) or they are phantoms (vanished-node)."""
        fresh: Dict[str, tuple] = {}
        old = self._mirror_node_fp
        folds = self._fold_mirror_nodes
        for name, ni in self.cache.nodes.items():
            entry = old.get(name)
            if (
                entry is not None
                and entry[0] == id(ni)
                and entry[1] == ni._ver
            ):
                fresh[name] = entry
                continue
            if ni.node is None:
                canonical = f"{name}|placeholder"
            else:
                canonical = (
                    f"{name}|{int(ni.ready())}|{_res_key(ni.allocatable)}"
                )
            d = _digest(canonical)
            b = _bucket_of(name, self.buckets)
            fresh[name] = (id(ni), ni._ver, d, b, canonical)
            if entry is not None:
                folds[entry[3]] ^= entry[2]
            folds[b] ^= d
        for name in old.keys() - fresh.keys():
            entry = old[name]
            folds[entry[3]] ^= entry[2]
        self._mirror_node_fp = fresh
        return fresh

    def _truth_pod_fps(self, pods, exempt):
        """{uid: (rv, digest, bucket, canonical)} over accepted cluster
        pods, excluding in-flight-exempt uids; fold maintained
        incrementally. Per-pod memoization on the cluster's write
        resourceVersion — an unchanged pod costs one dict get."""
        accept = self.cache._accept_pod
        fresh: Dict[str, tuple] = {}
        old = self._truth_pod_fp
        folds = self._fold_truth_pods
        B = self.buckets
        for pod in pods:
            uid = pod.metadata.uid
            if uid in exempt:
                continue
            entry = old.get(uid)
            rv = pod.metadata.resource_version
            if entry is not None and rv and entry[0] == rv:
                fresh[uid] = entry
                continue
            if not accept(pod):
                continue
            canonical = _task_canonical(TaskInfo(pod))
            if canonical is None:
                # Truth-side in-flight analog (deletion-stamped pod):
                # exempt this sweep.
                continue
            d = _digest(canonical)
            b = _bucket_of(uid, B)
            fresh[uid] = (rv, d, b, canonical)
            if entry is not None:
                folds[entry[2]] ^= entry[1]
            folds[b] ^= d
        for uid in old.keys() - fresh.keys():
            entry = old[uid]
            folds[entry[2]] ^= entry[1]
        self._truth_pod_fp = fresh
        return fresh

    def _truth_node_fps(self, nodes):
        from ..api import NodeInfo

        fresh: Dict[str, tuple] = {}
        old = self._truth_node_fp
        folds = self._fold_truth_nodes
        for node in nodes:
            name = node.name
            rv = node.metadata.resource_version
            entry = old.get(name)
            if entry is not None and rv and entry[0] == rv:
                fresh[name] = entry
                continue
            ni = NodeInfo(node)
            canonical = (
                f"{name}|{int(ni.ready())}|{_res_key(ni.allocatable)}"
            )
            d = _digest(canonical)
            b = _bucket_of(name, self.buckets)
            fresh[name] = (rv, d, b, canonical)
            if entry is not None:
                folds[entry[2]] ^= entry[1]
            folds[b] ^= d
        self._truth_node_fp = fresh
        return fresh

    # -- diffing -------------------------------------------------------------

    def _diff(self, dirty, mirror_jobs, truth_pods, mirror_nodes,
              truth_nodes) -> List[Tuple[str, str, str]]:
        """Object-level diff restricted to the dirty buckets (runs only
        when a bucket fold disagreed — never on a consistent sweep).
        Returns sorted (kind, subject, key) triples so repairs apply in
        a replay-deterministic order."""
        out: List[Tuple[str, str, str]] = []
        m_pods: Dict[str, tuple] = {}
        for entry in mirror_jobs.values():
            for uid, fp in entry[2].items():
                if fp[1] in dirty:
                    m_pods[uid] = fp
        t_pods = {
            uid: (e[1], e[2], e[3])
            for uid, e in truth_pods.items() if e[2] in dirty
        }
        for uid in sorted(m_pods.keys() | t_pods.keys()):
            m = m_pods.get(uid)
            t = t_pods.get(uid)
            if m is not None and t is None:
                out.append(("phantom-task", uid, uid))
            elif m is None and t is not None:
                bound = t[2].split("|", 3)[1] == "r"
                out.append((
                    "missed-bind" if bound else "missed-pod", uid, uid
                ))
            elif m[0] != t[0]:
                m_cls = m[2].split("|", 3)[1]
                t_cls = t[2].split("|", 3)[1]
                kind = (
                    "missed-bind" if t_cls == "r" and m_cls != "r"
                    else "stale-task"
                )
                out.append((kind, uid, uid))
        m_nodes = {
            name: (e[2], e[3], e[4])
            for name, e in mirror_nodes.items() if e[3] in dirty
        }
        t_nodes = {
            name: (e[1], e[2], e[3])
            for name, e in truth_nodes.items() if e[2] in dirty
        }
        for name in sorted(m_nodes.keys() | t_nodes.keys()):
            m = m_nodes.get(name)
            t = t_nodes.get(name)
            if m is not None and t is None:
                out.append(("vanished-node", name, name))
            elif m is None and t is not None:
                out.append(("missed-node", name, name))
            elif m[0] != t[0]:
                out.append(("stale-node", name, name))
        out.sort()
        return out

    # -- repair --------------------------------------------------------------

    def _repair(self, kind, subject, truth_pod_by_uid,
                truth_node_by_name, adopt_rvs: bool) -> bool:
        """One divergence repair through the stamping entry points.
        Returns True when the repair was applied. Never raises — one
        broken object must not stall the sweep (same contract as
        recovery.reconcile_journal)."""
        cache = self.cache
        try:
            if kind in ("missed-pod",):
                cache.add_pod(truth_pod_by_uid[subject])
            elif kind in ("missed-bind", "stale-task", "phantom-task"):
                with cache.mutex:
                    task = None
                    for job in cache.jobs.values():
                        task = job.tasks.get(subject)
                        if task is not None:
                            task = task.clone()
                            break
                if task is not None:
                    # _sync_task reconciles to cluster truth: updates
                    # to the live pod, or deletes when it vanished.
                    cache._sync_task(task)
                elif subject in truth_pod_by_uid:
                    cache.add_pod(truth_pod_by_uid[subject])
                else:
                    return False
            elif kind == "missed-node":
                cache.add_node(truth_node_by_name[subject])
            elif kind == "stale-node":
                node = truth_node_by_name[subject]
                cache.update_node(node, node)
            elif kind == "vanished-node":
                with cache.mutex:
                    ni = cache.nodes.get(subject)
                    node = ni.node if ni is not None else None
                if node is None:
                    from ..api import Node
                    from ..api.objects import ObjectMeta

                    node = Node(metadata=ObjectMeta(
                        name=subject, namespace="",
                    ))
                cache.delete_node(node)
            else:  # pragma: no cover - defensive
                return False
        except Exception:
            logger.exception(
                "anti-entropy repair %s of %s failed", kind, subject
            )
            return False
        return True

    # -- reporting -----------------------------------------------------------

    def _export(self, report: dict) -> None:
        """Metrics + flight-record annotation (never raises)."""
        try:
            from .. import metrics

            for kind in sorted(report["detected"]):
                metrics.register_divergence(
                    "detected", kind, report["detected"][kind]
                )
            for kind in sorted(report["repaired"]):
                metrics.register_divergence(
                    "repaired", kind, report["repaired"][kind]
                )
        except Exception:  # pragma: no cover - metrics must never kill
            logger.exception("divergence metric update failed")
        if report["detected"]:
            logger.warning(
                "anti-entropy sweep found divergence: %s (repaired %s, "
                "deferred %d)",
                report["detected"], report["repaired"],
                report["deferred"],
            )
            try:
                from ..obs import RECORDER

                RECORDER.annotate("integrity", {
                    "divergence_detected": dict(
                        sorted(report["detected"].items())
                    ),
                    "divergence_repaired": dict(
                        sorted(report["repaired"].items())
                    ),
                    "deferred": report["deferred"],
                })
            except Exception:  # pragma: no cover - forensics only
                logger.exception("integrity flight annotation failed")

    def state_dict(self) -> dict:
        return {
            "divergence_detected": dict(sorted(self.detected.items())),
            "divergence_repaired": dict(sorted(self.repaired.items())),
            "sweeps": self.sweeps,
            "last_sweep": dict(self.last_sweep),
        }
