"""Session: the per-cycle world view and decision surface.

Mirrors reference framework/session.go (:37 struct, :63 openSession,
:119 closeSession, :146 jobStatus, :194 Pipeline, :237 Allocate,
:294 dispatch, :321 Evict, :361 UpdateJobCondition) and
framework/session_plugins.go (tiered combinator dispatch).

The Session holds a deep-cloned snapshot; Allocate/Pipeline/Evict mutate the
snapshot and fire plugin event handlers; gang dispatch happens the moment a
job becomes Ready (session.go:281-289). This object is also what gets
vectorized into the dense tensor snapshot for the TPU solver (ops.snapshot).
"""

from __future__ import annotations

import logging
import time as _time
import uuid as _uuid
from typing import Callable, Dict, List, Optional

from .. import metrics
from ..api import (
    POD_GROUP_CONDITION_UNSCHEDULABLE,
    JobInfo,
    NodeInfo,
    PodGroupCondition,
    PodGroupPhase,
    QueueInfo,
    Resource,
    TaskInfo,
    TaskStatus,
    ValidateResult,
    allocated_status,
)
from ..conf import Tier
from .event import Event, EventHandler, JobBatchEvent

logger = logging.getLogger(__name__)

# Sub-phase wall times of the most recent allocate_batch (bench/perf
# forensics; the allocate_tpu action folds these into its last_stats).
last_apply_stats: dict = {}


def _move_tasks_logged(job, tasks, status, resreq_delta=None):
    """Bulk status move with the sequential loop's failure semantics: a
    group-level error degrades to per-task moves where each failure is
    logged and skipped instead of aborting the job's whole group.
    ``resreq_delta``, when given, is the exact aggregate resreq sum of
    ``tasks`` — the bulk path then updates ``job.allocated`` with ONE
    Resource op instead of one per task."""
    try:
        job.update_tasks_status(tasks, status, resreq_delta=resreq_delta)
    except Exception:
        for task in tasks:
            try:
                job.update_task_status(task, status)
            except Exception:
                logger.exception(
                    "Failed to move Task %s to %s", task.uid, status
                )


def _fold_job_batches(jobs_map, tasks):
    """Per-job :class:`JobBatchEvent` aggregates from a flat placed-task
    list (the slow path when no precomputed grouping hint is usable).
    Tasks whose job is unknown are logged and skipped."""
    by_job: Dict[str, JobBatchEvent] = {}
    for task in tasks:
        batch = by_job.get(task.job)
        if batch is None:
            job = jobs_map.get(task.job)
            if job is None:
                logger.warning(
                    "failed to find job %s for batch handlers", task.job
                )
                continue
            batch = by_job[task.job] = JobBatchEvent(
                job, [], Resource.empty()
            )
        batch.tasks.append(task)
        batch.delta.add(task.resreq)
    return list(by_job.values())


class Session:
    def __init__(
        self, cache, tiers: Optional[List[Tier]] = None,
        micro: bool = False,
    ):
        self.uid = str(_uuid.uuid4())
        self.cache = cache
        # Micro sessions tell the cache snapshot up front (the
        # ledger-verified fast path runs inside _open's snapshot call,
        # before run_micro could set the legacy micro_cycle attribute).
        self._micro = micro
        # Clone-touch ledger: uids/names of snapshot clones whose _ver
        # this session bumps (allocate/pipeline/evict/dispatch and
        # Statement ops). Reported to the cache at close so the next
        # micro snapshot's ledger verification rechecks exactly these
        # positions (cache.note_clones_touched).
        self._touched_jobs: set = set()
        self._touched_nodes: set = set()
        self.jobs: Dict[str, JobInfo] = {}
        self.nodes: Dict[str, NodeInfo] = {}
        self.queues: Dict[str, QueueInfo] = {}
        self.backlog: List[JobInfo] = []
        self.tiers: List[Tier] = tiers or []
        # Churn ledger from the cache snapshot (names touched since the
        # previous snapshot) — observability for incremental tensorize,
        # and (with the narrow subsets + generation) the warm-start
        # plan's delta preconditions (solver/warm.py).
        self.dirty_jobs: frozenset = frozenset()
        self.dirty_nodes: frozenset = frozenset()
        self.dirty_jobs_narrow: frozenset = frozenset()
        self.dirty_nodes_narrow: frozenset = frozenset()
        self.snap_gen: int = 0
        self._snap_total_allocatable = None
        # Event-driven micro cycle flag (Scheduler.run_micro): actions
        # place only through the warm path when set.
        self.micro_cycle = micro
        # The allocate_tpu AsyncSolveHandle currently in flight, if any
        # (drain guard: Statement boundaries and session close block on
        # it so no transaction or teardown races an outstanding solve).
        self._inflight_solve = None
        # Jobs whose conditions this session rewrote (update_job_condition)
        # — their close-time status write-back can never take the
        # unchanged-fingerprint skip.
        self._conditioned_jobs: set = set()

        self._total_allocatable: Optional[Resource] = None
        self.plugins: Dict[str, object] = {}
        self.event_handlers: List[EventHandler] = []
        self.job_order_fns: Dict[str, Callable] = {}
        self.queue_order_fns: Dict[str, Callable] = {}
        self.task_order_fns: Dict[str, Callable] = {}
        self.predicate_fns: Dict[str, Callable] = {}
        self.batch_predicate_fns: Dict[str, Callable] = {}
        self.batch_task_order_key_fns: Dict[str, Callable] = {}
        self.batch_job_order_key_fns: Dict[str, Callable] = {}
        self.preemptable_fns: Dict[str, Callable] = {}
        self.reclaimable_fns: Dict[str, Callable] = {}
        self.overused_fns: Dict[str, Callable] = {}
        self.job_ready_fns: Dict[str, Callable] = {}
        self.job_pipelined_fns: Dict[str, Callable] = {}
        self.job_valid_fns: Dict[str, Callable] = {}
        self.node_order_fns: Dict[str, List] = {}
        # TPU-solver seams: batched [T, N] score builders, per-queue budget
        # vectors, and weights for the scorers the kernel recomputes per
        # round (consumed by solver/snapshot.py).
        self.batch_node_order_fns: Dict[str, List] = {}
        self.queue_budget_fns: Dict[str, Callable] = {}
        # plugin name -> {scorer key -> weight} for in-kernel scorers
        self.solver_score_weights: Dict[str, Dict[str, float]] = {}

    # ------------------------------------------------------------------ open

    def _open(self) -> None:
        """reference session.go:63-117"""
        from ..obs import span

        with span("snapshot"):
            snapshot = self.cache.snapshot(micro=self._micro)
        self.jobs = snapshot.jobs
        self.nodes = snapshot.nodes
        self.queues = snapshot.queues
        self.dirty_jobs = getattr(snapshot, "dirty_jobs", frozenset())
        self.dirty_nodes = getattr(snapshot, "dirty_nodes", frozenset())
        self.dirty_jobs_narrow = getattr(
            snapshot, "dirty_jobs_narrow", frozenset()
        )
        self.dirty_nodes_narrow = getattr(
            snapshot, "dirty_nodes_narrow", frozenset()
        )
        self.snap_gen = getattr(snapshot, "snap_gen", 0)
        self._snap_total_allocatable = getattr(
            snapshot, "total_allocatable", None
        )

    def _validate_jobs(self) -> None:
        """Drop invalid jobs, persisting an Unschedulable condition
        (reference session.go:89-108). Called after plugins are opened so
        JobValid callbacks are installed."""
        for job in list(self.jobs.values()):
            # Fingerprint memo: a job that passed validation last cycle
            # and has not been mutated since passes again (JobValid
            # callbacks are pure functions of job state). Only PASSING
            # verdicts are memoized — invalid jobs re-run the full path
            # (condition write-back carries this session's transition
            # id). The attr lives on the clone, which the COW pool only
            # reuses while untouched, so a fresh clone self-invalidates.
            if getattr(job, "_valid_ok_ver", None) == job._ver:
                continue
            vr = self.job_valid(job)
            if vr is None or vr.passed:
                job._valid_ok_ver = job._ver
            if vr is not None and not vr.passed:
                cond = PodGroupCondition(
                    type=POD_GROUP_CONDITION_UNSCHEDULABLE,
                    status="True",
                    transition_id=self.uid,
                    reason=vr.reason,
                    message=vr.message,
                )
                try:
                    self.update_job_condition(job, cond)
                except KeyError:
                    logger.exception("failed to update job condition")
                del self.jobs[job.uid]

    def _close(self) -> None:
        """reference session.go:119-144"""
        if self._touched_jobs or self._touched_nodes:
            self.cache.note_clones_touched(
                self._touched_jobs, self._touched_nodes
            )
            self._touched_jobs = set()
            self._touched_nodes = set()
        conditioned = self._conditioned_jobs
        for job in self.jobs.values():
            if job.pod_group is None:
                self.cache.record_job_status_event(job)
                continue
            # Status write-back memo: an untouched job's recomputed
            # PodGroup status is identical to what the last close wrote
            # (status is a pure function of the task-status index, and
            # the unschedulable-condition term only fires for jobs
            # conditioned THIS session — tracked separately). The attr
            # lives on the clone; any mutation re-clones or bumps _ver.
            if (
                getattr(job, "_status_synced_ver", None) == job._ver
                and job.uid not in conditioned
                # An UNKNOWN phase decays once its condition's session
                # passes (the transition-id term) — never memoized.
                and job.pod_group.status.phase != PodGroupPhase.UNKNOWN
            ):
                continue
            job.pod_group.status = self._job_status(job)
            try:
                self.cache.update_job_status(job)
                job._status_synced_ver = job._ver
            except Exception:
                logger.exception(
                    "failed to update job <%s/%s>", job.namespace, job.name
                )
        self.jobs = {}
        self.nodes = {}
        self.backlog = []
        self._total_allocatable = None
        self.plugins = {}
        self.event_handlers = []
        self.job_order_fns = {}
        self.queue_order_fns = {}
        self.task_order_fns = {}
        self.predicate_fns = {}
        self.batch_predicate_fns = {}
        self.batch_task_order_key_fns = {}
        self.batch_job_order_key_fns = {}
        self.preemptable_fns = {}
        self.reclaimable_fns = {}
        self.overused_fns = {}
        self.job_ready_fns = {}
        self.job_pipelined_fns = {}
        self.job_valid_fns = {}
        self.node_order_fns = {}
        self.batch_node_order_fns = {}
        self.queue_budget_fns = {}
        self.solver_score_weights = {}

    def _job_status(self, job: JobInfo):
        """Recompute PodGroup status (reference session.go:146-184)."""
        status = job.pod_group.status
        unschedulable = any(
            c.type == POD_GROUP_CONDITION_UNSCHEDULABLE
            and c.status == "True"
            and c.transition_id == self.uid
            for c in status.conditions
        )
        if job.task_status_index.get(TaskStatus.RUNNING) and unschedulable:
            status.phase = PodGroupPhase.UNKNOWN
        else:
            allocated = sum(
                len(tasks)
                for st, tasks in job.task_status_index.items()
                if allocated_status(st)
            )
            if allocated >= job.pod_group.spec.min_member:
                status.phase = PodGroupPhase.RUNNING
            else:
                status.phase = PodGroupPhase.PENDING
        status.running = len(job.task_status_index.get(TaskStatus.RUNNING, {}))
        status.failed = len(job.task_status_index.get(TaskStatus.FAILED, {}))
        status.succeeded = len(job.task_status_index.get(TaskStatus.SUCCEEDED, {}))
        return status

    # ------------------------------------------------------- state mutation

    def statement(self) -> "Statement":
        from .statement import Statement

        return Statement(self)

    # ------------------------------------------- async-solve drain guard

    def register_inflight_solve(self, handle) -> None:
        """Track (or clear, with None) the action's in-flight async
        solve. While registered, any Statement commit/discard and the
        session close DRAIN the solve first — the overlapped cycle can
        never leak an outstanding device computation across a
        transaction boundary or session teardown."""
        self._inflight_solve = handle

    def drain_inflight_solve(self) -> None:
        """Block until any registered async solve is out of flight
        (no-op in the common already-fetched case)."""
        handle = self._inflight_solve
        if handle is not None:
            handle.drain()
            self._inflight_solve = None

    def total_node_allocatable(self) -> Resource:
        """Sum of ``allocatable`` over ALL session nodes (ready or not),
        computed once per session and shared — drf and proportion each
        paid their own O(nodes) accumulation pass at session open.
        Returns a fresh clone per call; callers own the result."""
        total = self._total_allocatable
        if total is None:
            # The cache maintains this sum across snapshots (O(churn)
            # adjustments in the pool walk); only a pre-maintenance
            # snapshot pays the O(nodes) accumulation here.
            total = self._snap_total_allocatable
            if total is None:
                total = Resource.empty()
                for node in self.nodes.values():
                    total.add(node.allocatable)
            self._total_allocatable = total
        return total.clone()

    def pipeline(self, task: TaskInfo, hostname: str) -> None:
        """Place onto releasing resources, session-only (session.go:194-234)."""
        job = self.jobs.get(task.job)
        if job is None:
            raise KeyError(f"failed to find job {task.job} when pipelining")
        job.update_task_status(task, TaskStatus.PIPELINED)
        self._touched_jobs.add(task.job)
        task.node_name = hostname
        node = self.nodes.get(hostname)
        if node is None:
            raise KeyError(f"failed to find node {hostname}")
        node.add_task(task)
        self._touched_nodes.add(hostname)
        for eh in self.event_handlers:
            if eh.allocate_func is not None:
                eh.allocate_func(Event(task))

    def allocate(self, task: TaskInfo, hostname: str) -> None:
        """Allocate in-session; dispatch the whole gang once JobReady
        (reference session.go:237-292)."""
        self.cache.allocate_volumes(task, hostname)
        job = self.jobs.get(task.job)
        if job is None:
            raise KeyError(f"failed to find job {task.job}")
        job.update_task_status(task, TaskStatus.ALLOCATED)
        self._touched_jobs.add(task.job)
        task.node_name = hostname
        node = self.nodes.get(hostname)
        if node is None:
            raise KeyError(f"failed to find node {hostname}")
        node.add_task(task)
        self._touched_nodes.add(hostname)
        for eh in self.event_handlers:
            if eh.allocate_func is not None:
                eh.allocate_func(Event(task))
        if self.job_ready(job):
            # Copy: dispatch mutates the Allocated index while we iterate.
            for t in list(
                job.task_status_index.get(TaskStatus.ALLOCATED, {}).values()
            ):
                self.dispatch(t)

    def allocate_batch(self, pairs) -> int:
        """Apply a solved assignment set in one pass: the batched
        equivalent of calling :meth:`allocate` per task, for the
        allocate_tpu apply phase (VERDICT r2: 50k sequential allocate()
        calls dominate the cycle).

        ``pairs`` is ``[(task, hostname), ...]`` in global priority order.
        Semantics preserved vs the sequential loop:

        - per-task volume assumption and node/job bookkeeping, in order;
        - plugin event handlers observe every allocation (batched form
          when the handler provides one, per-event otherwise);
        - gang dispatch: a job whose allocations make it JobReady has ALL
          its Allocated tasks dispatched (sequentially this happens the
          moment the gang crosses minAvailable and then after each later
          allocate — the end state, every Allocated task of a ready job
          dispatched, is identical);
        - per-task failures are logged and skipped, not fatal.

        Returns the number of tasks allocated.

        Thin wrapper: groups the pairs per hostname and delegates to
        :meth:`allocate_batch_grouped` (one implementation of the apply
        tail — events, handlers, gang dispatch — not two to keep in
        sync). allocate_tpu builds the groups itself from the solver's
        arrays and calls the grouped form directly."""
        staged: Dict[str, list] = {}  # hostname -> [tasks]
        for task, hostname in pairs:
            group = staged.get(hostname)
            if group is None:
                group = staged[hostname] = []
            group.append(task)
        return self.allocate_batch_grouped(
            [(hostname, tasks, None) for hostname, tasks in staged.items()]
        )

    def allocate_batch_grouped(self, node_groups, job_groups=None) -> int:
        """Apply a solved assignment set from PRE-GROUPED per-node lists
        — the zero-regroup fast path for allocate_tpu, whose fit guard
        already computed the per-node segmentation with numpy.

        ``node_groups`` is ``[(hostname, [tasks], delta)]`` where
        ``delta`` is the group's precomputed aggregate resreq (or None);
        tasks carry no node_name yet. ``job_groups``, when given, is the
        same assignment set PRE-GROUPED per job —
        ``[(job_uid, [tasks], delta)]`` with ``delta`` the exact
        aggregate resreq sum — so the apply tail skips the 50k per-task
        regroup pass, the per-task ``job.allocated`` arithmetic, AND the
        per-task plugin handler calls (aggregate JobBatchEvents go to
        ``batch_allocate_func`` handlers instead). The hint is trusted
        only while staging places every hinted task; any volume failure,
        vanished node/job, or node-accounting fallback drops back to the
        per-task fold so handler state can never drift from placements.

        Semantics are :meth:`allocate_batch`'s (volumes, status moves,
        node accounting, plugin events, gang dispatch); only the staging
        differs. Returns the number of tasks allocated."""
        last_apply_stats.clear()
        t0 = _time.perf_counter()
        hint_ok = job_groups is not None
        staged_total = 0
        alloc_groups: List[tuple] = []  # (hostname, node, [tasks], delta)
        for hostname, tasks, delta in node_groups:
            node = self.nodes.get(hostname)
            if node is None:
                logger.warning("failed to find node %s", hostname)
                hint_ok = False
                continue
            ok = self.cache.allocate_volumes_batch(
                tasks, hostname, assign_node_name=True
            )
            staged_total += len(ok)
            if len(ok) != len(tasks):
                hint_ok = False
            if ok:
                self._touched_nodes.add(hostname)
            alloc_groups.append((
                hostname, node, ok, delta if len(ok) == len(tasks) else None
            ))
        if hint_ok:
            hint_ok = staged_total == sum(
                len(group) for _, group, _ in job_groups
            ) and all(self.jobs.get(uid) is not None
                      for uid, _, _ in job_groups)
        # Per-job ALLOCATED moves: from the hint when valid (one
        # aggregate Resource op per job), else grouped with one
        # argsort-free pass (tasks of one job may span many nodes).
        jobs_by_uid: Dict[str, JobInfo] = {}
        job_batches: Optional[List[JobBatchEvent]] = None
        if hint_ok:
            job_batches = []
            for uid, group, delta in job_groups:
                job = self.jobs[uid]
                jobs_by_uid[uid] = job
                self._touched_jobs.add(uid)
                # Whole-bucket fast path: the solver's tasks ARE the
                # job's stored PENDING tasks (tensorize hands it the
                # bucket values), so a group covering the whole bucket
                # moves the bucket dict itself — no per-task
                # verification or re-insert (spot-checked on the first
                # task so a caller passing clones degrades safely).
                bucket = job.task_status_index.get(TaskStatus.PENDING)
                if (
                    bucket is not None
                    and len(bucket) == len(group)
                    and bucket.get(group[0].uid) is group[0]
                ):
                    try:
                        job.move_status_bucket(
                            TaskStatus.PENDING,
                            TaskStatus.ALLOCATED,
                            resreq_delta=delta,
                        )
                    except Exception:
                        logger.exception(
                            "bucket move failed for job %s; retrying "
                            "per task", uid,
                        )
                        _move_tasks_logged(
                            job, group, TaskStatus.ALLOCATED,
                            resreq_delta=delta,
                        )
                else:
                    _move_tasks_logged(
                        job, group, TaskStatus.ALLOCATED, resreq_delta=delta
                    )
                job_batches.append(JobBatchEvent(job, group, delta))
        else:
            by_job: Dict[str, list] = {}
            for _, _, tasks, _ in alloc_groups:
                for task in tasks:
                    group = by_job.get(task.job)
                    if group is None:
                        group = by_job[task.job] = []
                    group.append(task)
            for uid, group in by_job.items():
                job = self.jobs.get(uid)
                if job is None:
                    logger.warning("failed to find job %s", uid)
                    continue
                jobs_by_uid[uid] = job
                self._touched_jobs.add(uid)
                _move_tasks_logged(job, group, TaskStatus.ALLOCATED)
        t1 = _time.perf_counter()
        last_apply_stats["stage_ms"] = (t1 - t0) * 1e3

        placed_all: List[TaskInfo] = []
        for hostname, node, tasks, delta in alloc_groups:
            if delta is not None:
                try:
                    node.add_tasks_prevalidated(tasks, delta)
                    placed_all.extend(tasks)
                    continue
                except Exception:
                    logger.exception(
                        "prevalidated group rejected by node %s; "
                        "falling back to guarded add", hostname,
                    )
            placed_list = node.add_tasks_with_fallback(tasks)
            if len(placed_list) != len(tasks):
                job_batches = None  # hint no longer matches placements
            placed_all.extend(placed_list)
        t2 = _time.perf_counter()
        last_apply_stats["account_ms"] = (t2 - t1) * 1e3
        if not placed_all:
            return 0
        # Observability for the bench (BENCH attribution): aggregate
        # handler dispatch vs per-event, and whether the caller's
        # precomputed job grouping survived staging intact.
        last_apply_stats["handlers_batched"] = any(
            eh.batch_allocate_func is not None for eh in self.event_handlers
        )
        last_apply_stats["job_groups_hint"] = job_batches is not None
        self._fire_allocate_handlers(placed_all, job_batches)
        t3 = _time.perf_counter()
        last_apply_stats["handlers_ms"] = (t3 - t2) * 1e3

        dispatch_groups: List[tuple] = []
        for uid, job in jobs_by_uid.items():
            if self.job_ready(job):
                dispatch_groups.append((job, list(
                    job.task_status_index.get(
                        TaskStatus.ALLOCATED, {}
                    ).values()
                )))
        if dispatch_groups:
            self.dispatch_batch_grouped(dispatch_groups)
        last_apply_stats["dispatch_ms"] = (
            _time.perf_counter() - t3
        ) * 1e3
        return len(placed_all)

    def _fire_allocate_handlers(self, placed_all, job_batches) -> None:
        """Dispatch allocate events: aggregate JobBatchEvents to handlers
        with a batch form (folding them from ``placed_all`` when no valid
        pre-grouped hint survived staging), per-task Events to the rest."""
        batch_fns = [
            eh.batch_allocate_func
            for eh in self.event_handlers
            if eh.batch_allocate_func is not None
        ]
        legacy_fns = [
            eh.allocate_func
            for eh in self.event_handlers
            if eh.batch_allocate_func is None and eh.allocate_func is not None
        ]
        if batch_fns:
            if job_batches is None:
                job_batches = _fold_job_batches(self.jobs, placed_all)
            for fn in batch_fns:
                fn(job_batches)
        if legacy_fns:
            events = [Event(task) for task in placed_all]
            for fn in legacy_fns:
                for ev in events:
                    fn(ev)

    def dispatch_batch_grouped(self, groups) -> None:
        """Bind ready gangs from per-job groups: bulk BINDING moves per
        job (no regrouping pass), one batched metrics observe, one
        bind_batch submission."""
        all_ready: List[TaskInfo] = []
        for job, tasks in groups:
            # bind_volumes is a no-op for ready-volume tasks (the
            # overwhelming majority: claims-less pods) — scan first so
            # the common all-ready gang skips the per-task try/except.
            all_vols_ready = True
            for task in tasks:
                if not task.volume_ready:
                    all_vols_ready = False
                    break
            if all_vols_ready:
                ready = tasks
            else:
                ready = []
                for task in tasks:
                    if not task.volume_ready:
                        try:
                            self.cache.bind_volumes(task)
                        except Exception:
                            logger.exception(
                                "Failed to bind volumes of %s", task.uid
                            )
                            continue
                    ready.append(task)
            if not ready:
                continue
            # Whole-bucket fast path (see allocate_batch_grouped): a
            # ready gang's dispatch group IS its ALLOCATED bucket, so
            # move the bucket dict instead of re-verifying per task.
            # Allocated → Binding never flips allocated-status, so no
            # Resource math either way.
            bucket = job.task_status_index.get(TaskStatus.ALLOCATED)
            if (
                bucket is not None
                and len(bucket) == len(ready)
                and bucket.get(ready[0].uid) is ready[0]
            ):
                ready = job.move_status_bucket(
                    TaskStatus.ALLOCATED, TaskStatus.BINDING
                )
            else:
                _move_tasks_logged(job, ready, TaskStatus.BINDING)
            self._touched_jobs.add(job.uid)
            all_ready.extend(ready)
        # Latency is measured creation → dispatch (reference
        # session.go:316), so capture `now` here; but observe only the
        # tasks whose cache bookkeeping ACCEPTED the bind (the callback
        # fires from the bookkeeping worker), so validation failures and
        # node-rejected reverts don't inflate scheduled counts.
        now = _time.time()
        self.cache.bind_batch(
            all_ready,
            on_accepted=lambda accepted: (
                metrics.update_task_schedule_durations([
                    max(0.0, now - t.pod.metadata.creation_timestamp)
                    for t in accepted
                ])
            ),
        )

    def dispatch(self, task: TaskInfo) -> None:
        """Bind one gang member (reference session.go:294-318)."""
        self.cache.bind_volumes(task)
        self.cache.bind(task, task.node_name)
        job = self.jobs.get(task.job)
        if job is None:
            raise KeyError(f"failed to find job {task.job}")
        job.update_task_status(task, TaskStatus.BINDING)
        self._touched_jobs.add(task.job)
        # Time from pod creation to bind (reference session.go:316).
        metrics.update_task_schedule_duration(
            max(0.0, _time.time() - task.pod.metadata.creation_timestamp)
        )

    def dispatch_batch(self, tasks: List[TaskInfo]) -> None:
        """Bind a whole ready gang with one cache round trip (one mutex
        hold, one async side-effect job) instead of per-task dispatch.
        Thin wrapper: groups per job and delegates to
        :meth:`dispatch_batch_grouped`."""
        by_job: Dict[str, list] = {}
        for task in tasks:
            group = by_job.get(task.job)
            if group is None:
                group = by_job[task.job] = []
            group.append(task)
        groups = []
        for uid, group in by_job.items():
            job = self.jobs.get(uid)
            if job is None:
                logger.warning("failed to find job %s", uid)
                continue
            groups.append((job, group))
        if groups:
            self.dispatch_batch_grouped(groups)

    def evict(self, reclaimee: TaskInfo, reason: str) -> None:
        """Direct eviction (reference session.go:321-358)."""
        self.cache.evict(reclaimee, reason)
        job = self.jobs.get(reclaimee.job)
        if job is None:
            raise KeyError(f"failed to find job {reclaimee.job}")
        job.update_task_status(reclaimee, TaskStatus.RELEASING)
        self._touched_jobs.add(reclaimee.job)
        node = self.nodes.get(reclaimee.node_name)
        if node is not None:
            node.update_task(reclaimee)
            self._touched_nodes.add(reclaimee.node_name)
        for eh in self.event_handlers:
            if eh.deallocate_func is not None:
                eh.deallocate_func(Event(reclaimee))

    def evict_batch(
        self, reclaimees: List[TaskInfo], reason: str
    ) -> List[TaskInfo]:
        """Batched :meth:`evict`: cache side effects and node accounting
        keep their per-task semantics (each failure logged and skipped,
        not fatal — the degraded form of evict()'s raise), while the job
        status moves are bulked per job with one aggregate ``allocated``
        update, and plugin deallocate handlers fire ONCE with per-job
        :class:`JobBatchEvent` aggregates (per-event fallback for
        handlers without a batch form). Returns the tasks actually
        evicted (callers sum their resreqs to see what was freed)."""
        by_job: Dict[str, list] = {}
        for task in reclaimees:
            group = by_job.get(task.job)
            if group is None:
                group = by_job[task.job] = []
            group.append(task)
        batches: List[JobBatchEvent] = []
        for uid, group in by_job.items():
            job = self.jobs.get(uid)
            if job is None:
                logger.warning("failed to find job %s when evicting", uid)
                continue
            evicted: List[TaskInfo] = []
            delta = Resource.empty()
            for task in group:
                try:
                    self.cache.evict(task, reason)
                except Exception:
                    logger.exception("Failed to evict Task %s", task.uid)
                    continue
                evicted.append(task)
                delta.add(task.resreq)
            if not evicted:
                continue
            _move_tasks_logged(
                job, evicted, TaskStatus.RELEASING, resreq_delta=delta
            )
            self._touched_jobs.add(uid)
            for task in evicted:
                node = self.nodes.get(task.node_name)
                if node is not None:
                    node.update_task(task)
                    self._touched_nodes.add(task.node_name)
            batches.append(JobBatchEvent(job, evicted, delta))
        if not batches:
            return []
        legacy_events: Optional[List[Event]] = None
        for eh in self.event_handlers:
            if eh.batch_deallocate_func is not None:
                eh.batch_deallocate_func(batches)
            elif eh.deallocate_func is not None:
                if legacy_events is None:
                    legacy_events = [
                        Event(t) for b in batches for t in b.tasks
                    ]
                for ev in legacy_events:
                    eh.deallocate_func(ev)
        return [t for b in batches for t in b.tasks]

    def update_job_condition(self, job_info: JobInfo, cond: PodGroupCondition) -> None:
        """reference session.go:361-383"""
        job = self.jobs.get(job_info.uid)
        if job is None:
            raise KeyError(
                f"failed to find job <{job_info.namespace}/{job_info.name}>"
            )
        self._conditioned_jobs.add(job_info.uid)
        if job.pod_group is None:
            # Legacy PDB-sourced jobs have no PodGroup to carry conditions
            # (the reference would nil-deref here, session.go:368 — we log
            # instead; the diagnosis still reaches the user via events).
            logger.debug(
                "job <%s/%s> has no PodGroup; dropping condition %s",
                job.namespace, job.name, cond.type,
            )
            return
        for i, c in enumerate(job.pod_group.status.conditions):
            if c.type == cond.type:
                job.pod_group.status.conditions[i] = cond
                return
        job.pod_group.status.conditions.append(cond)

    def add_event_handler(self, eh: EventHandler) -> None:
        self.event_handlers.append(eh)

    # ------------------------------------------- callback registration API

    def add_job_order_fn(self, name, fn):
        self.job_order_fns[name] = fn

    def add_queue_order_fn(self, name, fn):
        self.queue_order_fns[name] = fn

    def add_task_order_fn(self, name, fn):
        self.task_order_fns[name] = fn

    def add_predicate_fn(self, name, fn):
        self.predicate_fns[name] = fn

    def add_batch_predicate_fn(self, name, fn):
        """TPU-native extension: vectorized predicate producing a
        solver BatchMask (or legacy [T,N] bool array) for a whole task
        batch at once (consumed by solver.snapshot)."""
        self.batch_predicate_fns[name] = fn

    def add_batch_task_order_key_fn(self, name, fn):
        """TPU-native extension: (tasks) -> ascending sort-key array
        equivalent to the plugin's task_order_fn, enabling vectorized
        task ordering in the snapshot path."""
        self.batch_task_order_key_fns[name] = fn

    def add_batch_job_order_key_fn(self, name, fn):
        """TPU-native extension: (jobs) -> ascending sort-key array
        equivalent to the plugin's job_order_fn, enabling one numpy
        lexsort over a queue's jobs in the snapshot path instead of
        O(J log J) tiered comparison calls."""
        self.batch_job_order_key_fns[name] = fn

    def add_preemptable_fn(self, name, fn):
        self.preemptable_fns[name] = fn

    def add_reclaimable_fn(self, name, fn):
        """Register ``fn(reclaimer, reclaimees) -> victims``.

        Contract the in-tree reclaim action's per-queue exhausted-node
        memo depends on (actions/reclaim.py): within one cycle, a
        registered fn's verdict about a given reclaimee must be
        (a) CLAIMANT-INDEPENDENT — it may read the reclaimee's job/queue
        state but not compare against the reclaimer (proportion, gang
        and conformance all qualify; an upstream-style priority-vs-victim
        comparison would not) — and (b) EVICTION-MONOTONE — evictions
        performed during the cycle may only shrink (never grow) the
        victim set it would return for the same node, except through a
        successful claimant pipeline (which reclaim already handles by
        invalidating other queues' memos). The reclaim action detects
        fns outside the known-safe set and disables the memo for the
        cycle, so registering a fn that violates this contract costs
        throughput, not correctness — but keep the contract in mind
        when writing one."""
        self.reclaimable_fns[name] = fn

    def add_overused_fn(self, name, fn):
        self.overused_fns[name] = fn

    def add_job_ready_fn(self, name, fn):
        self.job_ready_fns[name] = fn

    def add_job_pipelined_fn(self, name, fn):
        self.job_pipelined_fns[name] = fn

    def add_job_valid_fn(self, name, fn):
        self.job_valid_fns[name] = fn

    def add_node_order_fn(self, name, fn, weight: float = 1.0):
        """Node scorers; (task, node) -> float, higher is better. The
        reference plumbs k8s PriorityConfigs (session_plugins.go:354-369);
        here scorers are plain weighted functions, and plugins may also
        attach a ``batch_fn`` via add_batch_node_order_fn for the TPU path."""
        self.node_order_fns.setdefault(name, []).append((fn, weight))

    def add_batch_node_order_fn(self, name, fn, weight: float = 1.0):
        """Batched scorer: (tasks, nodes) -> np.ndarray [T, N] of 0..10
        scores, summed (weighted) into the solver's static score matrix."""
        self.batch_node_order_fns.setdefault(name, []).append((fn, weight))

    def add_queue_budget_fn(self, name, fn):
        """Queue budget vectors for the solver: (queue) ->
        (deserved: Resource, allocated: Resource) or None if the plugin has
        no opinion (proportion's water-filled shares, proportion.go:100-147)."""
        self.queue_budget_fns[name] = fn

    # ------------------------------------------------- tiered combinators
    # reference framework/session_plugins.go

    def _enabled(self, flag: Optional[bool]) -> bool:
        return bool(flag)

    def reclaimable(self, reclaimer: TaskInfo, reclaimees: List[TaskInfo]):
        """Intersection within a tier; first deciding tier wins
        (session_plugins.go:80-119)."""
        return self._evictable(
            reclaimer, reclaimees, self.reclaimable_fns, "enabled_reclaimable"
        )

    def preemptable(self, preemptor: TaskInfo, preemptees: List[TaskInfo]):
        """session_plugins.go:121-162"""
        return self._evictable(
            preemptor, preemptees, self.preemptable_fns, "enabled_preemptable"
        )

    def _evictable(self, evictor, evictees, fns, flag_attr):
        # Go-nil semantics matter here (session_plugins.go:80-119): a plugin
        # answering "no victims" (nil) poisons every later intersection, and a
        # tier only decides when its running intersection is non-empty.
        victims: Optional[List[TaskInfo]] = None
        init = False
        for tier in self.tiers:
            for plugin in tier.plugins:
                if not self._enabled(getattr(plugin, flag_attr)):
                    continue
                fn = fns.get(plugin.name)
                if fn is None:
                    continue
                candidates = fn(evictor, evictees) or None  # empty → Go nil
                if not init:
                    victims = candidates
                    init = True
                elif victims:
                    cand_uids = {c.uid for c in (candidates or [])}
                    victims = [v for v in victims if v.uid in cand_uids] or None
            if victims is not None:
                return victims
        return victims or []

    def overused(self, queue: QueueInfo) -> bool:
        """Any-true across all tiers (session_plugins.go:164-179)."""
        for tier in self.tiers:
            for plugin in tier.plugins:
                fn = self.overused_fns.get(plugin.name)
                if fn is not None and fn(queue):
                    return True
        return False

    def job_ready(self, obj) -> bool:
        """All-true (session_plugins.go:182-200)."""
        for tier in self.tiers:
            for plugin in tier.plugins:
                if not self._enabled(plugin.enabled_job_ready):
                    continue
                fn = self.job_ready_fns.get(plugin.name)
                if fn is not None and not fn(obj):
                    return False
        return True

    def job_pipelined(self, obj) -> bool:
        """All-true (session_plugins.go:202-221)."""
        for tier in self.tiers:
            for plugin in tier.plugins:
                if not self._enabled(plugin.enabled_job_pipelined):
                    continue
                fn = self.job_pipelined_fns.get(plugin.name)
                if fn is not None and not fn(obj):
                    return False
        return True

    def job_valid(self, obj) -> Optional[ValidateResult]:
        """First failure wins (session_plugins.go:224-240)."""
        for tier in self.tiers:
            for plugin in tier.plugins:
                fn = self.job_valid_fns.get(plugin.name)
                if fn is None:
                    continue
                vr = fn(obj)
                if vr is not None and not vr.passed:
                    return vr
        return None

    def job_order_fn(self, l: JobInfo, r: JobInfo) -> bool:
        """First nonzero comparison; creation-time+UID tiebreak
        (session_plugins.go:243-267)."""
        for tier in self.tiers:
            for plugin in tier.plugins:
                if not self._enabled(plugin.enabled_job_order):
                    continue
                fn = self.job_order_fns.get(plugin.name)
                if fn is None:
                    continue
                j = fn(l, r)
                if j != 0:
                    return j < 0
        if l.creation_timestamp == r.creation_timestamp:
            return l.uid < r.uid
        return l.creation_timestamp < r.creation_timestamp

    def queue_order_fn(self, l: QueueInfo, r: QueueInfo) -> bool:
        """session_plugins.go:270-295"""
        for tier in self.tiers:
            for plugin in tier.plugins:
                if not self._enabled(plugin.enabled_queue_order):
                    continue
                fn = self.queue_order_fns.get(plugin.name)
                if fn is None:
                    continue
                j = fn(l, r)
                if j != 0:
                    return j < 0
        lt = l.queue.metadata.creation_timestamp
        rt = r.queue.metadata.creation_timestamp
        if lt == rt:
            return l.uid < r.uid
        return lt < rt

    def task_compare_fns(self, l: TaskInfo, r: TaskInfo) -> int:
        """session_plugins.go:298-315"""
        for tier in self.tiers:
            for plugin in tier.plugins:
                if not self._enabled(plugin.enabled_task_order):
                    continue
                fn = self.task_order_fns.get(plugin.name)
                if fn is None:
                    continue
                j = fn(l, r)
                if j != 0:
                    return j
        return 0

    def task_order_fn(self, l: TaskInfo, r: TaskInfo) -> bool:
        """session_plugins.go:318-331"""
        res = self.task_compare_fns(l, r)
        if res != 0:
            return res < 0
        lt = l.pod.metadata.creation_timestamp
        rt = r.pod.metadata.creation_timestamp
        if lt == rt:
            return l.uid < r.uid
        return lt < rt

    def predicate_fn(self, task: TaskInfo, node: NodeInfo) -> None:
        """All must pass; raises PredicateError on failure
        (session_plugins.go:334-351)."""
        for tier in self.tiers:
            for plugin in tier.plugins:
                if not self._enabled(plugin.enabled_predicate):
                    continue
                fn = self.predicate_fns.get(plugin.name)
                if fn is None:
                    continue
                fn(task, node)  # raises on failure

    def node_prioritizers(self) -> List:
        """Concat enabled scorers (session_plugins.go:354-369)."""
        configs: List = []
        for tier in self.tiers:
            for plugin in tier.plugins:
                if not self._enabled(plugin.enabled_node_order):
                    continue
                configs.extend(self.node_order_fns.get(plugin.name, []))
        return configs

    # ------------------------------------------- TPU-solver tier gating
    # The batched seams honor the same per-tier enable flags as their
    # scalar counterparts, so allocate and allocate_tpu see identical
    # policy for a given scheduler conf.

    def batch_task_order_keys(self, tasks):
        """List of ascending key arrays (tier order) reproducing
        task_order_fn, or None if an enabled task-order plugin has no
        batch key form (callers then fall back to comparison sorting)."""
        keys: List = []
        for tier in self.tiers:
            for plugin in tier.plugins:
                if not self._enabled(plugin.enabled_task_order):
                    continue
                if self.task_order_fns.get(plugin.name) is None:
                    continue
                kfn = self.batch_task_order_key_fns.get(plugin.name)
                if kfn is None:
                    return None
                keys.append(kfn(tasks))
        return keys

    def batch_job_order_keys(self, jobs):
        """List of ascending key arrays (tier order) reproducing
        job_order_fn, or None if an enabled job-order plugin has no
        batch key form (callers then fall back to comparison sorting).
        The (creation_timestamp, uid) tiebreak is the caller's, exactly
        as in :meth:`job_order_fn`."""
        keys: List = []
        for tier in self.tiers:
            for plugin in tier.plugins:
                if not self._enabled(plugin.enabled_job_order):
                    continue
                if self.job_order_fns.get(plugin.name) is None:
                    continue
                kfn = self.batch_job_order_key_fns.get(plugin.name)
                if kfn is None:
                    return None
                keys.append(kfn(jobs))
        return keys

    def batch_predicates(self) -> List:
        """(name, fn) of enabled batched predicates, tier-gated like
        predicate_fn."""
        out: List = []
        for tier in self.tiers:
            for plugin in tier.plugins:
                if not self._enabled(plugin.enabled_predicate):
                    continue
                fn = self.batch_predicate_fns.get(plugin.name)
                if fn is not None:
                    out.append((plugin.name, fn))
        return out

    def scalar_only_predicates(self) -> List:
        """(name, fn) of enabled scalar predicates that have NO batched
        form (fallback path for unported plugins)."""
        out: List = []
        for tier in self.tiers:
            for plugin in tier.plugins:
                if not self._enabled(plugin.enabled_predicate):
                    continue
                if plugin.name in self.batch_predicate_fns:
                    continue
                fn = self.predicate_fns.get(plugin.name)
                if fn is not None:
                    out.append((plugin.name, fn))
        return out

    def batch_node_prioritizers(self) -> List:
        """(fn, weight) of enabled batched scorers, tier-gated like
        node_prioritizers."""
        configs: List = []
        for tier in self.tiers:
            for plugin in tier.plugins:
                if not self._enabled(plugin.enabled_node_order):
                    continue
                configs.extend(self.batch_node_order_fns.get(plugin.name, []))
        return configs

    def solver_dynamic_weights(self) -> Dict[str, float]:
        """Merged in-kernel scorer weights from plugins whose node-order is
        enabled (zeroed otherwise, matching node_prioritizers gating)."""
        merged: Dict[str, float] = {}
        for tier in self.tiers:
            for plugin in tier.plugins:
                if not self._enabled(plugin.enabled_node_order):
                    continue
                for key, w in self.solver_score_weights.get(
                    plugin.name, {}
                ).items():
                    merged[key] = merged.get(key, 0.0) + w
        return merged

    def __repr__(self) -> str:
        return (
            f"Session {self.uid}: jobs={len(self.jobs)}, "
            f"nodes={len(self.nodes)}, queues={len(self.queues)}"
        )
