from .api import ADDED, DELETED, MODIFIED, ClusterAPI, InProcessCluster
