"""Session events (reference framework/event.go:24-32).

TPU-native extension: the batched apply path (Session.allocate_batch /
evict_batch) groups a whole solved assignment set per job and hands
plugin handlers :class:`JobBatchEvent` aggregates — one precomputed
``delta`` (the exact resreq sum of the batch) per job — so a 50k-task
apply costs the handlers ~#jobs Resource updates instead of 50k
per-task calls (the reference fires one event per task,
session.go:273-276, mirrored by drf.go:137-157's per-event handlers).
"""

from __future__ import annotations

from dataclasses import dataclass
from typing import Callable, List, Optional

from ..api import JobInfo, Resource, TaskInfo


@dataclass
class Event:
    task: TaskInfo


@dataclass
class JobBatchEvent:
    """One job's slice of a batched allocate/evict: the affected tasks
    plus their precomputed aggregate ``delta`` (sum of ``task.resreq``).

    ``delta`` is exact — resource quantities are integral milli-units /
    bytes, so the numpy/Python fold that builds it is bit-identical to
    summing the tasks one by one (same argument as the node accounting
    aggregates, NodeInfo.add_tasks_prevalidated).
    """

    job: JobInfo
    tasks: List[TaskInfo]
    delta: Resource


@dataclass
class EventHandler:
    allocate_func: Optional[Callable[[Event], None]] = None
    deallocate_func: Optional[Callable[[Event], None]] = None
    # TPU-native extension: aggregate batched forms, called ONCE with a
    # list of per-job JobBatchEvents by Session.allocate_batch_grouped /
    # evict_batch. A handler that provides a batch form must make it
    # equivalent to folding the per-event form over every task of every
    # batch (in order); handlers without one get the per-event fallback.
    batch_allocate_func: Optional[Callable[[List[JobBatchEvent]], None]] = None
    batch_deallocate_func: Optional[Callable[[List[JobBatchEvent]], None]] = None
