"""Cache interface + the four side-effect seams.

Mirrors reference pkg/scheduler/cache/interface.go:
- Cache (:26-55): Run, Snapshot, WaitForCacheSync, Bind, Evict,
  RecordJobStatusEvent, UpdateJobStatus, AllocateVolumes, BindVolumes.
- Binder/Evictor/StatusUpdater/VolumeBinder (:57-77) — the seams behind which
  all cluster I/O hides, making the decision core testable with zero cluster.
"""

from __future__ import annotations

import logging
from abc import ABC, abstractmethod
from typing import TYPE_CHECKING

if TYPE_CHECKING:
    from ..api import ClusterInfo, JobInfo, Pod, PodCondition, PodGroup, TaskInfo

logger = logging.getLogger(__name__)


class Binder(ABC):
    """reference interface.go:57-60"""

    @abstractmethod
    def bind(self, pod: "Pod", hostname: str) -> None: ...


class Evictor(ABC):
    """reference interface.go:62-65"""

    @abstractmethod
    def evict(self, pod: "Pod") -> None: ...


class StatusUpdater(ABC):
    """reference interface.go:67-71"""

    @abstractmethod
    def update_pod_condition(self, pod: "Pod", condition: "PodCondition") -> None: ...

    @abstractmethod
    def update_pod_group(self, pg: "PodGroup") -> None: ...


class VolumeBinder(ABC):
    """reference interface.go:73-77"""

    @abstractmethod
    def allocate_volumes(self, task: "TaskInfo", hostname: str) -> None: ...

    @abstractmethod
    def bind_volumes(self, task: "TaskInfo") -> None: ...

    def release_volumes(self, task: "TaskInfo") -> None:
        """Undo claim assumptions after a failed bind (default no-op;
        extension beyond the reference interface, needed because a
        timed-out bind must return its claims)."""
        return None


class Cache(ABC):
    """reference interface.go:26-55"""

    @abstractmethod
    def run(self, stop_event) -> None: ...

    @abstractmethod
    def wait_for_cache_sync(self, stop_event) -> bool: ...

    @abstractmethod
    def snapshot(self) -> "ClusterInfo": ...

    @abstractmethod
    def bind(self, task: "TaskInfo", hostname: str) -> None: ...

    def bind_batch(self, task_infos, on_accepted=None) -> list:
        """Batched bind (TPU-native extension): one bookkeeping pass + one
        async side-effect job for a whole gang. Default falls back to
        per-task bind(); SchedulerCache overrides with the real batch.
        Each task must carry node_name. Returns the tasks submitted;
        ``on_accepted`` (if given) is invoked — possibly later, from a
        worker thread — with the subset whose cache bookkeeping actually
        succeeded, so callers can observe per-task metrics without
        counting validation failures or node-rejected reverts."""
        bound = []
        for ti in task_infos:
            try:
                self.bind(ti, ti.node_name)
                bound.append(ti)
            except Exception:  # parity with bind_batch's skip-and-log
                logger.exception(
                    "failed to bind task %s/%s", ti.namespace, ti.name
                )
        if on_accepted is not None:
            try:
                on_accepted(bound)
            except Exception:  # same contract as the async batch path
                logger.exception("bind_batch on_accepted callback failed")
        return bound

    @abstractmethod
    def evict(self, task: "TaskInfo", reason: str) -> None: ...

    @abstractmethod
    def record_job_status_event(self, job: "JobInfo") -> None: ...

    @abstractmethod
    def update_job_status(self, job: "JobInfo") -> "JobInfo": ...

    @abstractmethod
    def allocate_volumes(self, task: "TaskInfo", hostname: str) -> None: ...

    def allocate_volumes_batch(self, tasks, hostname: str) -> list:
        """Batched volume allocation for one node's group (TPU-native
        extension). Default falls back to per-task allocate_volumes;
        SchedulerCache overrides with the claims-aware fast path.
        Returns the tasks that succeeded."""
        ok = []
        for task in tasks:
            try:
                self.allocate_volumes(task, hostname)
            except Exception:
                logger.exception(
                    "failed to allocate volumes of %s/%s",
                    task.namespace, task.name,
                )
                continue
            ok.append(task)
        return ok

    @abstractmethod
    def bind_volumes(self, task: "TaskInfo") -> None: ...
