"""Version information.

Mirrors reference pkg/version/version.go (:28-35 — vars injected via
ldflags, Makefile:7-10; printed for --version). Here the build metadata is
set at import time with optional environment overrides (the setuptools/
Makefile analog of ldflags injection).
"""

from __future__ import annotations

import os
import platform
import sys

RELEASE_VERSION = os.environ.get("TPU_BATCH_VERSION", "0.1.0")
GIT_SHA = os.environ.get("TPU_BATCH_GIT_SHA", "unknown")
BUILT = os.environ.get("TPU_BATCH_BUILT", "unknown")


def print_version_and_exit(apiserver_version: str = "") -> None:
    """reference version.go:38-47 PrintVersionAndExit"""
    print(version_string())
    raise SystemExit(0)


def version_string() -> str:
    lines = [
        f"tpu-batch version: {RELEASE_VERSION}",
        f"  git sha: {GIT_SHA}",
        f"  built:   {BUILT}",
        f"  python:  {sys.version.split()[0]} on {platform.platform()}",
    ]
    try:
        import jax

        lines.append(f"  jax:     {jax.__version__}")
    except Exception:  # pragma: no cover - jax is a hard dep in practice
        pass
    return "\n".join(lines)
