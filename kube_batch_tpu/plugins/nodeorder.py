"""Nodeorder plugin: node scoring.

Mirrors reference plugins/nodeorder/nodeorder.go (:129-171), which installs
k8s prioritizers LeastRequested, BalancedResourceAllocation, NodeAffinity and
InterPodAffinity with weights from plugin arguments
{nodeaffinity,podaffinity,leastrequested,balancedresource}.weight
(:86-126). Scorers are implemented natively with the standard k8s formulas
(0..10 per scorer, weighted sum).

Reference bug NOT replicated: nodeorder.go:160,:166 passes
balancedRescourceWeight for NodeAffinity and InterPodAffinity; here each
scorer uses its own weight.
"""

from __future__ import annotations

from ..api import NodeInfo, TaskInfo
from ..framework import Plugin, register_plugin_builder
from .util import (
    match_affinity_term,
    match_node_selector_terms,
)

MAX_PRIORITY = 10.0

# Argument keys (reference nodeorder.go:75-84).
NODE_AFFINITY_WEIGHT = "nodeaffinity.weight"
POD_AFFINITY_WEIGHT = "podaffinity.weight"
LEAST_REQUESTED_WEIGHT = "leastrequested.weight"
BALANCED_RESOURCE_WEIGHT = "balancedresource.weight"


def least_requested_score(task: TaskInfo, node: NodeInfo) -> float:
    """k8s least_requested_priority: mean over cpu/mem of
    (capacity - requested) * 10 / capacity."""
    cpu_cap = node.allocatable.milli_cpu
    mem_cap = node.allocatable.memory
    cpu_req = node.used.milli_cpu + task.resreq.milli_cpu
    mem_req = node.used.memory + task.resreq.memory
    cpu_score = (
        max(0.0, (cpu_cap - cpu_req)) * MAX_PRIORITY / cpu_cap if cpu_cap > 0 else 0.0
    )
    mem_score = (
        max(0.0, (mem_cap - mem_req)) * MAX_PRIORITY / mem_cap if mem_cap > 0 else 0.0
    )
    return (cpu_score + mem_score) / 2.0


def balanced_resource_score(task: TaskInfo, node: NodeInfo) -> float:
    """k8s balanced_resource_allocation: 10 - |cpuFraction - memFraction|*10."""
    cpu_cap = node.allocatable.milli_cpu
    mem_cap = node.allocatable.memory
    cpu_frac = (
        (node.used.milli_cpu + task.resreq.milli_cpu) / cpu_cap if cpu_cap > 0 else 1.0
    )
    mem_frac = (node.used.memory + task.resreq.memory) / mem_cap if mem_cap > 0 else 1.0
    if cpu_frac >= 1.0 or mem_frac >= 1.0:
        return 0.0
    return MAX_PRIORITY - abs(cpu_frac - mem_frac) * MAX_PRIORITY


def node_affinity_score(task: TaskInfo, node: NodeInfo) -> float:
    """k8s CalculateNodeAffinityPriority: sum of matching preferred-term
    weights, normalized later by the caller across nodes; here normalized to
    0..10 by total preferred weight."""
    affinity = task.pod.spec.affinity
    if affinity is None or not affinity.node_preferred:
        return 0.0
    labels = node.node.metadata.labels if node.node else {}
    total = sum(t.get("weight", 1) for t in affinity.node_preferred)
    if total <= 0:
        return 0.0
    score = 0.0
    for term in affinity.node_preferred:
        if match_node_selector_terms(term.get("expressions"), labels):
            score += term.get("weight", 1)
    return score * MAX_PRIORITY / total


def make_inter_pod_affinity_score(ssn):
    """Preferred pod-affinity: +1 per matching session pod already on the
    node (normalized to 0..10 by count of terms)."""

    def inter_pod_affinity_score(task: TaskInfo, node: NodeInfo) -> float:
        affinity = task.pod.spec.affinity
        if affinity is None or not affinity.pod_affinity:
            return 0.0
        from .util import SessionPodLister

        on_node = SessionPodLister(ssn).pods_on_node(node.name)
        if not on_node:
            return 0.0
        matched = 0
        for term in affinity.pod_affinity:
            if any(
                match_affinity_term(term, t.pod.metadata.labels)
                for t in on_node
            ):
                matched += 1
        return matched * MAX_PRIORITY / len(affinity.pod_affinity)

    return inter_pod_affinity_score


class NodeOrderPlugin(Plugin):
    def __init__(self, arguments=None):
        self.arguments = arguments or {}

    def name(self) -> str:
        return "nodeorder"

    def _weight(self, key: str, default: int = 1) -> float:
        get_int = getattr(self.arguments, "get_int", None)
        if get_int is None:
            return float(default)
        return float(get_int(key, default))

    def on_session_open(self, ssn) -> None:
        ssn.add_node_order_fn(
            self.name(), least_requested_score, self._weight(LEAST_REQUESTED_WEIGHT)
        )
        ssn.add_node_order_fn(
            self.name(),
            balanced_resource_score,
            self._weight(BALANCED_RESOURCE_WEIGHT),
        )
        ssn.add_node_order_fn(
            self.name(), node_affinity_score, self._weight(NODE_AFFINITY_WEIGHT)
        )
        ssn.add_node_order_fn(
            self.name(),
            make_inter_pod_affinity_score(ssn),
            self._weight(POD_AFFINITY_WEIGHT),
        )

        # TPU solver path: LeastRequested/Balanced depend on the evolving
        # idle vectors, so the kernel recomputes them in-round from these
        # weights (keyed by plugin name so tier enablement can gate them);
        # the affinity scorers are static per session and are delivered as
        # a batched [T, N] matrix.
        ssn.solver_score_weights[self.name()] = {
            "leastrequested": self._weight(LEAST_REQUESTED_WEIGHT),
            "balancedresource": self._weight(BALANCED_RESOURCE_WEIGHT),
        }

        import numpy as np

        inter_pod = make_inter_pod_affinity_score(ssn)
        na_weight = self._weight(NODE_AFFINITY_WEIGHT)
        pa_weight = self._weight(POD_AFFINITY_WEIGHT)

        def batch_affinity_scores(tasks, nodes):
            """Sparse per-task score rows: only tasks carrying preferred
            node affinity or pod affinity contribute (solver/masks.py
            combine_score_rows folds the dict into the device inputs)."""
            N = len(nodes)
            rows = {}
            for i, task in enumerate(tasks):
                aff = task.pod.spec.affinity
                if aff is None or not (aff.node_preferred or aff.pod_affinity):
                    continue
                row = np.empty(N, dtype=np.float32)
                for j, node in enumerate(nodes):
                    row[j] = (
                        node_affinity_score(task, node) * na_weight
                        + inter_pod(task, node) * pa_weight
                    )
                rows[i] = row
            return rows

        ssn.add_batch_node_order_fn(self.name(), batch_affinity_scores)


register_plugin_builder("nodeorder", lambda args: NodeOrderPlugin(args))
