"""CLI/process layer tests (reference cmd/kube-batch/app).

Covers flag parsing (options.go), the /metrics HTTP endpoint
(server.go:86-89), file-lease leader election (server.go:96-141 analog),
the cluster-state loader, and a full end-to-end --once run through
``cli.run`` binding a gang onto the in-process cluster.
"""

import json
import os
import threading
import time
import urllib.request

import pytest

from kube_batch_tpu.cli import (
    LeaderElector,
    ServerOption,
    build_cluster_from_dict,
    load_cluster_state,
    parse_options,
    run,
    start_metrics_server,
)
from kube_batch_tpu.version import version_string

EXAMPLE_STATE = {
    "queues": [{"name": "default", "weight": 1}],
    "nodes": [
        {"name": "n1", "allocatable": {"cpu": "8", "memory": "16Gi", "pods": "110"}},
        {"name": "n2", "allocatable": {"cpu": "8", "memory": "16Gi", "pods": "110"}},
    ],
    "podGroups": [
        {"name": "pg1", "namespace": "default", "minMember": 3, "queue": "default"}
    ],
    "pods": [
        {"name": f"p{i}", "namespace": "default", "group": "pg1",
         "requests": {"cpu": "1000m", "memory": "1Gi"}}
        for i in range(3)
    ],
}


def test_parse_options_defaults():
    opt = parse_options([])
    assert opt.scheduler_name == "tpu-batch"
    assert opt.schedule_period == 1.0
    assert opt.default_queue == "default"
    assert opt.listen_address == ":8080"
    assert opt.enable_priority_class
    assert not opt.enable_leader_election


def test_parse_options_flags():
    opt = parse_options([
        "--scheduler-name", "x", "--schedule-period", "0.25",
        "--default-queue", "q", "--leader-elect",
        "--lock-object-namespace", "/tmp/locks", "--no-priority-class",
        "--once",
    ])
    assert opt.scheduler_name == "x"
    assert opt.schedule_period == 0.25
    assert opt.default_queue == "q"
    assert opt.enable_leader_election
    assert opt.lock_object_namespace == "/tmp/locks"
    assert not opt.enable_priority_class
    assert opt.once


def test_check_option_or_die():
    opt = ServerOption(enable_leader_election=True, lock_object_namespace="")
    with pytest.raises(ValueError):
        opt.check_option_or_die()


def test_version_string():
    s = version_string()
    assert "tpu-batch version" in s


def test_cluster_state_loader(tmp_path):
    import yaml

    path = tmp_path / "state.yaml"
    path.write_text(yaml.safe_dump(EXAMPLE_STATE))
    cluster = load_cluster_state(str(path))
    assert len(cluster.list_objects("Node")) == 2
    assert len(cluster.list_objects("Pod")) == 3
    assert len(cluster.list_objects("PodGroup")) == 1
    assert len(cluster.list_objects("Queue")) == 1


def test_metrics_http_endpoint():
    server, _ = start_metrics_server("127.0.0.1:0")
    try:
        port = server.server_address[1]
        body = urllib.request.urlopen(
            f"http://127.0.0.1:{port}/metrics", timeout=5
        ).read().decode()
        assert "tpu_batch_e2e_scheduling_latency_seconds" in body
        health = urllib.request.urlopen(
            f"http://127.0.0.1:{port}/healthz", timeout=5
        ).read()
        assert health == b"ok\n"
    finally:
        server.shutdown()


def test_leader_election_exclusive(tmp_path):
    a = LeaderElector(str(tmp_path), "a", lease_duration=5.0)
    b = LeaderElector(str(tmp_path), "b", lease_duration=5.0)
    assert a.try_acquire()
    assert not b.try_acquire()
    # Stale lease (older than lease_duration) may be stolen.
    with open(a.lock_path) as f:
        lease = json.load(f)
    lease["renew_ts"] = time.time() - 10.0
    with open(a.lock_path, "w") as f:
        json.dump(lease, f)
    assert b.try_acquire()
    assert not a.try_acquire()
    b.release()
    assert not os.path.exists(b.lock_path)


def test_run_once_binds_gang():
    """Full process path: cli.run --once schedules the example gang."""
    cluster = build_cluster_from_dict(EXAMPLE_STATE)
    opt = ServerOption(
        enable_leader_election=False, once=True,
        listen_address="127.0.0.1:0",
    )
    run(opt, cluster=cluster)
    pods = cluster.list_objects("Pod")
    bound = [p for p in pods if p.spec.node_name]
    assert len(bound) == 3
    # simulate_kubelet flips bound pods to Running.
    assert all(p.status.phase == "Running" for p in bound)


def test_run_with_leader_election(tmp_path):
    """Leader-elected run executes the loop and can be stopped."""
    cluster = build_cluster_from_dict(EXAMPLE_STATE)
    opt = ServerOption(
        enable_leader_election=True,
        lock_object_namespace=str(tmp_path),
        once=True,
        listen_address="127.0.0.1:0",
    )
    done = threading.Event()

    def target():
        run(opt, cluster=cluster)
        done.set()

    t = threading.Thread(target=target, daemon=True)
    t.start()
    assert done.wait(timeout=30)
    bound = [p for p in cluster.list_objects("Pod") if p.spec.node_name]
    assert len(bound) == 3
    # Lease file is released after run.
    assert not os.path.exists(os.path.join(str(tmp_path), "tpu-batch-leader.lock"))
