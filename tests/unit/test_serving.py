"""Serving-subsystem units (doc/design/serving.md): the annotation/
label schema parses totally (malformed values degrade, never raise),
node-class feasibility verdicts, the combine-level bit-parity contract
(an all-default BatchMask folds in as structurally nothing), the
serving plugin's mask/score compilation and preempt/reclaim gate, and
the ledger's per-class SLO accounting + violation budget."""

import numpy as np
import pytest

from kube_batch_tpu.api.serving import (
    CAPACITY_SPOT,
    DEFAULT_NODE_CLASS,
    MIN_TOPOLOGY_TIER_ANNOTATION_KEY,
    REPLICA_FLOOR_ANNOTATION_KEY,
    RESERVED_ONLY_ANNOTATION_KEY,
    SLO_SECONDS_ANNOTATION_KEY,
    TOPOLOGY_TIER_LABEL_KEY,
    TPU_GENERATION_LABEL_KEY,
    TPU_GENERATIONS_ANNOTATION_KEY,
    CAPACITY_TYPE_LABEL_KEY,
    WORKLOAD_CLASS_ANNOTATION_KEY,
    NodeClass,
    ServingSLO,
    node_class_from_labels,
    parse_serving_slo,
    parse_workload_class,
    slo_permits_node,
)
from kube_batch_tpu.obs.latency import PlacementLedger
from kube_batch_tpu.plugins import serving as serving_mod
from kube_batch_tpu.plugins.serving import (
    MAX_PRIORITY,
    PREEMPT_OVERRIDE_ENV,
    ServingPlugin,
    node_class_score,
)
from kube_batch_tpu.plugins.util import PredicateError
from kube_batch_tpu.solver.masks import BatchMask, combine_masks

SERVING_ANN = {WORKLOAD_CLASS_ANNOTATION_KEY: "serving"}


# ---------------------------------------------------------------- parsing


class TestParsing:
    def test_workload_class_defaults_to_batch(self):
        assert parse_workload_class({}) == "batch"
        assert parse_workload_class(None) == "batch"
        assert parse_workload_class(
            {WORKLOAD_CLASS_ANNOTATION_KEY: "inference"}
        ) == "batch"
        assert parse_workload_class(SERVING_ANN) == "serving"

    def test_batch_pod_has_no_slo(self):
        assert parse_serving_slo({}) is None
        assert parse_serving_slo(
            {SLO_SECONDS_ANNOTATION_KEY: "2.0"}
        ) is None  # SLO annotations without the class opt-in are inert

    def test_full_slo_parses(self):
        slo = parse_serving_slo({
            **SERVING_ANN,
            SLO_SECONDS_ANNOTATION_KEY: "1.5",
            REPLICA_FLOOR_ANNOTATION_KEY: "3",
            TPU_GENERATIONS_ANNOTATION_KEY: "v5e, v5p",
            MIN_TOPOLOGY_TIER_ANNOTATION_KEY: "2",
            RESERVED_ONLY_ANNOTATION_KEY: "1",
        })
        assert slo == ServingSLO(
            target_seconds=1.5, replica_floor=3,
            generations=frozenset({"v5e", "v5p"}),
            min_topology_tier=2, reserved_only=True,
        )
        assert slo.constrains_nodes()

    def test_malformed_values_degrade_not_raise(self):
        slo = parse_serving_slo({
            **SERVING_ANN,
            SLO_SECONDS_ANNOTATION_KEY: "fast",
            REPLICA_FLOOR_ANNOTATION_KEY: "-3",
            TPU_GENERATIONS_ANNOTATION_KEY: " , ",
            MIN_TOPOLOGY_TIER_ANNOTATION_KEY: "high",
            RESERVED_ONLY_ANNOTATION_KEY: "yes",
        })
        assert slo == ServingSLO()
        assert not slo.constrains_nodes()

    def test_unlabeled_node_is_the_shared_default_class(self):
        # Identity matters: clones share one object, and a batch-only
        # cluster must not allocate a NodeClass per node.
        assert node_class_from_labels({}) is DEFAULT_NODE_CLASS
        assert node_class_from_labels(None) is DEFAULT_NODE_CLASS
        assert node_class_from_labels(
            {TOPOLOGY_TIER_LABEL_KEY: "junk"}
        ) is DEFAULT_NODE_CLASS

    def test_node_labels_parse(self):
        nc = node_class_from_labels({
            TPU_GENERATION_LABEL_KEY: "v5p",
            TOPOLOGY_TIER_LABEL_KEY: "3",
            CAPACITY_TYPE_LABEL_KEY: "spot",
        })
        assert nc == NodeClass(
            generation="v5p", topology_tier=3, capacity=CAPACITY_SPOT
        )
        assert nc.spot


# ------------------------------------------------------------ feasibility


class TestFeasibility:
    def test_unconstrained_permits_everything(self):
        slo = ServingSLO(target_seconds=1.0)
        assert slo_permits_node(slo, DEFAULT_NODE_CLASS)
        assert slo_permits_node(slo, NodeClass(capacity=CAPACITY_SPOT))

    def test_generation_whitelist(self):
        slo = ServingSLO(generations=frozenset({"v5p"}))
        assert slo_permits_node(slo, NodeClass(generation="v5p"))
        assert not slo_permits_node(slo, NodeClass(generation="v5e"))
        assert not slo_permits_node(slo, DEFAULT_NODE_CLASS)  # unlabeled

    def test_min_topology_tier(self):
        slo = ServingSLO(min_topology_tier=2)
        assert not slo_permits_node(slo, NodeClass(topology_tier=1))
        assert slo_permits_node(slo, NodeClass(topology_tier=2))

    def test_reserved_only_excludes_spot(self):
        slo = ServingSLO(reserved_only=True)
        assert slo_permits_node(slo, DEFAULT_NODE_CLASS)
        assert not slo_permits_node(
            slo, NodeClass(capacity=CAPACITY_SPOT)
        )

    def test_node_class_score_shape(self):
        assert node_class_score(NodeClass(capacity=CAPACITY_SPOT)) == 0.0
        assert node_class_score(DEFAULT_NODE_CLASS) == MAX_PRIORITY / 2
        assert node_class_score(
            NodeClass(topology_tier=4)
        ) == MAX_PRIORITY
        # Tier preference saturates instead of growing unboundedly.
        assert node_class_score(
            NodeClass(topology_tier=9)
        ) == MAX_PRIORITY
        spot_hi = node_class_score(
            NodeClass(capacity=CAPACITY_SPOT, topology_tier=4)
        )
        assert spot_hi == MAX_PRIORITY / 2  # spot never beats reserved


# -------------------------------------------------- combine-level parity


class TestMaskParity:
    def test_default_batchmask_is_structurally_absent(self):
        T, N = 7, 5
        with_plugin = combine_masks([BatchMask()], T, N)
        without = combine_masks([], T, N)
        for attr in (
            "node_ok", "task_group", "group_rows", "pair_idx", "pair_rows"
        ):
            a = getattr(with_plugin, attr)
            b = getattr(without, attr)
            assert a.dtype == b.dtype
            assert np.array_equal(a, b), attr

    def test_group_rows_fold_matches_dense(self):
        T, N = 4, 6
        rng = np.random.RandomState(3)
        rows = np.vstack([
            np.ones(N, dtype=bool), rng.rand(N) > 0.4, rng.rand(N) > 0.4,
        ])
        mask = BatchMask(
            task_group=np.array([0, 1, 2, 1], dtype=np.int32),
            group_rows=rows,
        )
        combined = combine_masks([mask], T, N)
        dense = mask.dense(T, N)
        for i in range(T):
            assert np.array_equal(combined.row(i), dense[i])


# ------------------------------------------------- plugin compilation

class StubTask:
    def __init__(self, job):
        self.job = job


class StubNode:
    def __init__(self, name, node_class):
        self.name = name
        self.node_class = node_class


class StubJob:
    def __init__(self, slo=None, ready=0):
        self.slo = slo
        self._ready = ready

    def ready_task_num(self):
        return self._ready


class StubSession:
    """Records the callbacks ServingPlugin registers."""

    def __init__(self, jobs):
        self.jobs = jobs
        self.fns = {}

    def add_predicate_fn(self, name, fn):
        self.fns["predicate"] = fn

    def add_batch_predicate_fn(self, name, fn):
        self.fns["batch_predicate"] = fn

    def add_node_order_fn(self, name, fn, weight=1.0):
        self.fns["node_order"] = fn

    def add_batch_node_order_fn(self, name, fn, weight=1.0):
        self.fns["batch_node_order"] = fn

    def add_preemptable_fn(self, name, fn):
        self.fns["preemptable"] = fn

    def add_reclaimable_fn(self, name, fn):
        self.fns["reclaimable"] = fn


def open_stub_session(jobs):
    ssn = StubSession(jobs)
    ServingPlugin().on_session_open(ssn)
    return ssn


RESERVED_SLO = ServingSLO(target_seconds=1.0, reserved_only=True)


def mixed_fixture():
    """2 batch tasks + 3 serving (two sharing one spec) over 4 nodes,
    one of them spot."""
    jobs = {
        "b": StubJob(),
        "s1": StubJob(slo=RESERVED_SLO),
        "s2": StubJob(slo=RESERVED_SLO),
        "s3": StubJob(slo=ServingSLO(generations=frozenset({"v5p"}))),
    }
    tasks = [
        StubTask("b"), StubTask("s1"), StubTask("b"),
        StubTask("s2"), StubTask("s3"),
    ]
    nodes = [
        StubNode("n0", DEFAULT_NODE_CLASS),
        StubNode("n1", NodeClass(capacity=CAPACITY_SPOT)),
        StubNode("n2", NodeClass(generation="v5p")),
        StubNode("n3", NodeClass(generation="v5p",
                                 capacity=CAPACITY_SPOT)),
    ]
    return jobs, tasks, nodes


class TestPluginCompilation:
    def test_batch_only_snapshot_compiles_to_default_mask(self):
        ssn = open_stub_session({"b": StubJob()})
        tasks = [StubTask("b"), StubTask("b")]
        nodes = [StubNode("n0", DEFAULT_NODE_CLASS)]
        mask = ssn.fns["batch_predicate"](tasks, nodes)
        assert isinstance(mask, BatchMask)
        assert mask.node_ok is None
        assert mask.task_group is None
        assert mask.group_rows is None
        assert mask.rows == {}
        # ...and the scorer contributes no rows either.
        assert ssn.fns["batch_node_order"](tasks, nodes) == {}

    def test_signature_sharing_and_verdicts(self):
        jobs, tasks, nodes = mixed_fixture()
        ssn = open_stub_session(jobs)
        mask = ssn.fns["batch_predicate"](tasks, nodes)
        # Group 0 is the unconstrained row; s1/s2 share one signature
        # row, s3 gets its own: 3 rows total, not 1-per-task.
        assert mask.group_rows.shape == (3, len(nodes))
        tg = mask.task_group
        assert tg[0] == tg[2] == 0            # batch tasks unconstrained
        assert tg[1] == tg[3]                 # shared spec -> shared row
        assert tg[4] not in (0, tg[1])
        dense = mask.dense(len(tasks), len(nodes))
        for i, task in enumerate(tasks):
            slo = jobs[task.job].slo
            for j, node in enumerate(nodes):
                want = slo is None or slo_permits_node(
                    slo, node.node_class
                )
                assert dense[i, j] == want, (i, j)

    def test_score_rows_only_for_serving_tasks(self):
        jobs, tasks, nodes = mixed_fixture()
        ssn = open_stub_session(jobs)
        rows = ssn.fns["batch_node_order"](tasks, nodes)
        assert sorted(rows) == [1, 3, 4]
        # One shared per-node row (the score depends only on the node).
        assert rows[1] is rows[3] is rows[4]
        expect = [node_class_score(n.node_class) for n in nodes]
        assert rows[1].dtype == np.float32
        assert np.allclose(rows[1], expect)

    def test_scalar_predicate_mirrors_the_mask(self):
        jobs, tasks, nodes = mixed_fixture()
        ssn = open_stub_session(jobs)
        pred = ssn.fns["predicate"]
        pred(tasks[0], nodes[1])          # batch task: anything goes
        pred(tasks[1], nodes[0])          # reserved node ok
        with pytest.raises(PredicateError):
            pred(tasks[1], nodes[1])      # spot violates reserved_only
        with pytest.raises(PredicateError):
            pred(tasks[4], nodes[0])      # unlabeled violates gen pin


# ------------------------------------------------------- eviction gate


class BudgetStub:
    def __init__(self, bad_jobs=()):
        self.bad = set(bad_jobs)

    def serving_budget_ok(self, job):
        return job not in self.bad


class TestEvictionGate:
    def gate(self, jobs, monkeypatch, bad_jobs=()):
        monkeypatch.setattr(
            serving_mod, "LEDGER", BudgetStub(bad_jobs)
        )
        ssn = open_stub_session(jobs)
        assert ssn.fns["preemptable"] is ssn.fns["reclaimable"]
        return ssn.fns["preemptable"]

    def test_batch_victims_pass_through(self, monkeypatch):
        gate = self.gate({"b": StubJob()}, monkeypatch)
        victims = [StubTask("b"), StubTask("b")]
        assert gate(StubTask("x"), victims) == victims

    def test_replica_floor_blocks_eviction(self, monkeypatch):
        slo = ServingSLO(replica_floor=2)
        jobs = {
            "at-floor": StubJob(slo=slo, ready=2),
            "above": StubJob(slo=slo, ready=3),
        }
        gate = self.gate(jobs, monkeypatch)
        at_floor, above = StubTask("at-floor"), StubTask("above")
        out = gate(StubTask("x"), [at_floor, above])
        assert out == [above]  # taking "at-floor" below 2 is barred

    def test_budget_burn_blocks_eviction(self, monkeypatch):
        jobs = {"s": StubJob(slo=ServingSLO(target_seconds=1.0), ready=9)}
        gate = self.gate(jobs, monkeypatch, bad_jobs={"s"})
        assert gate(StubTask("x"), [StubTask("s")]) == []

    def test_override_disables_the_gate(self, monkeypatch):
        monkeypatch.setenv(PREEMPT_OVERRIDE_ENV, "1")
        jobs = {"s": StubJob(slo=ServingSLO(replica_floor=5), ready=5)}
        gate = self.gate(jobs, monkeypatch, bad_jobs={"s"})
        victims = [StubTask("s")]
        assert gate(StubTask("x"), victims) == victims


# ------------------------------------------------------ ledger accounting


class FakeClock:
    def __init__(self):
        self.t = 100.0

    def __call__(self):
        return self.t


def place(ledger, clock, uid, job, wait, queue="serving"):
    ledger.note_placed([(uid, job)], {job: queue})
    ledger.note_dispatched([uid])
    clock.t += wait
    ledger.note_applied(uid)


class TestLedgerAccounting:
    def make(self):
        ledger = PlacementLedger()
        clock = FakeClock()
        ledger.configure(enabled=True, clock=clock)
        return ledger, clock

    def test_met_missed_and_attainment(self):
        ledger, clock = self.make()
        ledger.note_arrival(
            "u1", "ns/s-0", "ns/s", workload_class="serving",
            slo_target=1.0,
        )
        ledger.note_arrival(
            "u2", "ns/s-1", "ns/s", workload_class="serving",
            slo_target=1.0,
        )
        place(ledger, clock, "u1", "ns/s", wait=0.5)   # met
        place(ledger, clock, "u2", "ns/s", wait=2.0)   # missed
        s = ledger.serving_summary()
        cls = s["classes"]["serving"]
        assert cls["placed"] == 2
        assert cls["met"] == 1
        assert cls["missed"] == 1
        assert cls["attainment_pct"] == 50.0
        assert s["violations"] == 1
        assert s["budget_burn"] > 1.0     # 1 miss vs 0.02 allowed
        # ...and the burning job may no longer donate capacity.
        assert not ledger.serving_budget_ok("ns/s")
        assert ledger.serving_budget_ok("ns/other")  # untargeted passes

    def test_pressure_and_arrival_pending(self):
        ledger, clock = self.make()
        assert not ledger.serving_pressure()
        ledger.note_arrival(
            "u1", "ns/s-0", "ns/s", workload_class="serving",
            slo_target=1.0,
        )
        # Arrival-pending is a consume-once micro-cycle wakeup signal.
        assert ledger.serving_arrival_pending()
        assert not ledger.serving_arrival_pending()
        assert not ledger.serving_pressure()  # deadline not yet passed
        clock.t += 1.5
        assert ledger.serving_pressure()
        place(ledger, clock, "u1", "ns/s", wait=0.0)
        assert not ledger.serving_pressure()

    def test_batch_arrivals_never_engage_serving_accounting(self):
        ledger, clock = self.make()
        ledger.note_arrival("u1", "ns/b-0", "ns/b")
        place(ledger, clock, "u1", "ns/b", wait=5.0, queue="batch")
        s = ledger.serving_summary()
        assert s["classes"] == {}
        assert s["violations"] == 0
        assert "serving_slo_miss_rate" not in ledger.telemetry_sample()
