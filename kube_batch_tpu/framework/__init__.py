"""Framework kernel (mirrors reference pkg/scheduler/framework)."""

from .arguments import Arguments
from .event import Event, EventHandler, JobBatchEvent
from .framework import close_session, open_session
from .interface import Action, Plugin
from .plugins import (
    cleanup_plugin_builders,
    get_action,
    get_plugin_builder,
    register_action,
    register_plugin_builder,
)
from .session import Session
from .statement import Statement
