#!/usr/bin/env python
"""`make latency-smoke`: prove the placement-latency SLI pipeline is
ENGAGED and replay-stable, end to end (doc/design/observability.md §5).

Three assertions over one short high-arrival sim run + its replay:

1. **ledger engaged** — the run stamped a nonzero number of pods at
   arrival and carried them to bind-applied (report.latency.stamped /
   .applied > 0, total-stage p99 present);
2. **telemetry carries the series** — the soak telemetry dump's rolled
   windows contain at least one ``placement_p99:<queue>`` key (the
   series the soak drift detector bounds) and the ``latency_entries``
   watermark;
3. **audit stream replay-stable** — the decision-audit JSONL parses,
   every record carries the deterministic core fields, and replaying
   the recorded trace emits a BYTE-IDENTICAL stream (the virtual-clock
   stamping contract; wall clock never enters a record).

Exit codes: 0 clean; 1 a sim run failed; 2 engagement assert failed;
3 telemetry assert failed; 4 audit parse/byte-stability failed.
"""

from __future__ import annotations

import json
import os
import subprocess
import sys
import tempfile

REPO = os.path.dirname(os.path.dirname(os.path.abspath(__file__)))


def run_sim(args, label):
    proc = subprocess.run(
        [sys.executable, "-m", "kube_batch_tpu", "sim"] + args,
        cwd=REPO, capture_output=True, text=True, timeout=600,
    )
    if proc.returncode != 0:
        print(f"latency-smoke: {label} sim exited "
              f"{proc.returncode}", file=sys.stderr)
        print(proc.stderr[-2000:], file=sys.stderr)
        sys.exit(1)
    return proc


def main() -> int:
    tmp = tempfile.mkdtemp(prefix="kbt-latency-smoke-")
    trace = os.path.join(tmp, "run.jsonl")
    audit_a = os.path.join(tmp, "audit-record.jsonl")
    audit_b = os.path.join(tmp, "audit-replay.jsonl")
    telemetry = os.path.join(tmp, "telemetry.json")
    report_path = os.path.join(tmp, "report.json")

    base = [
        "--cycles", "60", "--seed", "19", "--backend", "native",
        "--arrival-profile", "burst", "--burst-size", "24",
        "--burst-every", "8", "--arrival-rate", "2",
        "--max-jobs-in-flight", "256",
        "--fail-on-cycle-errors", "--quiet",
    ]
    run_sim(base + [
        "--trace", trace, "--audit-out", audit_a,
        "--soak", "--telemetry-out", telemetry,
        "--report-out", report_path,
    ], "record")

    # 1. ledger engaged.
    with open(report_path) as f:
        report = json.load(f)
    lat = report.get("latency") or {}
    if not (lat.get("stamped") and lat.get("applied")):
        print(f"latency-smoke: ledger did NOT engage "
              f"(latency={lat})", file=sys.stderr)
        return 2
    stage_p99 = lat.get("stage_p99_s") or {}
    if "total" not in stage_p99 or stage_p99["total"] <= 0:
        print(f"latency-smoke: no total-stage p99 recorded "
              f"(stage_p99_s={stage_p99})", file=sys.stderr)
        return 2
    print(
        f"latency-smoke: ledger engaged — {lat['stamped']} stamped, "
        f"{lat['applied']} applied, total p99 "
        f"{stage_p99['total']:.3f}s (virtual), "
        f"{lat.get('gang_samples', 0)} gang sample(s)"
    )

    # 2. telemetry carries the placement series.
    with open(telemetry) as f:
        tele = json.load(f)
    keys = set()
    for window in tele.get("windows", []):
        keys.update(window.get("keys", {}))
    p99_keys = sorted(k for k in keys if k.startswith("placement_p99:"))
    if not p99_keys or "latency_entries" not in keys:
        print(f"latency-smoke: telemetry windows missing the placement "
              f"series (p99 keys={p99_keys}, "
              f"latency_entries={'latency_entries' in keys})",
              file=sys.stderr)
        return 3
    print(f"latency-smoke: telemetry series present — {p99_keys}")

    # 3. audit stream: parses, deterministic core fields, byte-equal
    # under replay.
    with open(audit_a, "rb") as f:
        raw_a = f.read()
    records = [json.loads(line) for line in raw_a.decode().splitlines()]
    if not records:
        print("latency-smoke: audit dump is empty", file=sys.stderr)
        return 4
    required = {"seq", "cycle", "kind", "vclock", "action", "job",
                "queue", "count"}
    for rec in records:
        missing = required - set(rec)
        if missing:
            print(f"latency-smoke: audit record missing fields "
                  f"{sorted(missing)}: {rec}", file=sys.stderr)
            return 4

    run_sim([
        "--replay", trace, "--backend", "native",
        "--audit-out", audit_b, "--fail-on-cycle-errors", "--quiet",
    ], "replay")
    with open(audit_b, "rb") as f:
        raw_b = f.read()
    if raw_a != raw_b:
        a_lines, b_lines = raw_a.splitlines(), raw_b.splitlines()
        for i, (la, lb) in enumerate(zip(a_lines, b_lines)):
            if la != lb:
                print(f"latency-smoke: audit streams DIVERGE at record "
                      f"{i}:\n  record: {la.decode()[:200]}\n  replay: "
                      f"{lb.decode()[:200]}", file=sys.stderr)
                break
        else:
            print(f"latency-smoke: audit streams differ in length "
                  f"({len(a_lines)} vs {len(b_lines)} records)",
                  file=sys.stderr)
        return 4
    print(f"latency-smoke: audit stream byte-identical under replay "
          f"({len(records)} records)")
    return 0


if __name__ == "__main__":
    sys.exit(main())
