"""Explainability tests: per-cycle verdicts from the real allocate_tpu
action (predicate-blocked and gang-minMember-break gangs), the deep
per-predicate diagnosis, the explain CLI, and the /debug/jobs surface.
"""

import json
import urllib.request

import pytest

from kube_batch_tpu import metrics
from kube_batch_tpu.api import PodPhase, build_resource_list
from kube_batch_tpu.cache import SchedulerCache
from kube_batch_tpu.framework import close_session, get_action, open_session
from kube_batch_tpu.obs import explain
from kube_batch_tpu.utils.test_utils import (
    FakeBinder,
    FakeEvictor,
    FakeStatusUpdater,
    FakeVolumeBinder,
    build_node,
    build_pod,
    build_pod_group,
    build_queue,
)
from tests.actions.test_actions import make_tiers

TIERS_ARGS = (
    ["priority", "gang", "conformance"],
    ["drf", "predicates", "proportion", "nodeorder"],
)


@pytest.fixture(autouse=True)
def _clean_registry():
    explain.clear()
    yield
    explain.clear()


def _cache():
    return SchedulerCache(
        binder=FakeBinder(),
        evictor=FakeEvictor(),
        status_updater=FakeStatusUpdater(),
        volume_binder=FakeVolumeBinder(),
    )


def _run_allocate_tpu(cache):
    ssn = open_session(cache, make_tiers(*TIERS_ARGS))
    action, _ = get_action("allocate_tpu")
    action.execute(ssn)
    return ssn


def _blocked_gang_cache():
    """A 3-member gang whose nodeSelector matches no node."""
    cache = _cache()
    cache.add_queue(build_queue("default", weight=1))
    for name in ("n1", "n2"):
        cache.add_node(build_node(
            name,
            build_resource_list(cpu="8", memory="16Gi", pods=110),
            labels={"zone": "a"},
        ))
    cache.add_pod_group(build_pod_group(
        "blocked", namespace="t", min_member=3, queue="default"
    ))
    for i in range(3):
        cache.add_pod(build_pod(
            "t", f"b{i}", "", PodPhase.PENDING,
            build_resource_list(cpu="1000m", memory="1Gi"),
            group_name="blocked",
            selector={"zone": "nowhere"},
        ))
    return cache


def _minmember_gang_cache():
    """A 3-member gang where only 2 members can ever fit (6 CPU tasks
    on two 8-CPU nodes): feasible nodes exist, but minMember breaks."""
    cache = _cache()
    cache.add_queue(build_queue("default", weight=1))
    for name in ("n1", "n2"):
        cache.add_node(build_node(
            name, build_resource_list(cpu="8", memory="16Gi", pods=110)
        ))
    cache.add_pod_group(build_pod_group(
        "biggang", namespace="t", min_member=3, queue="default"
    ))
    for i in range(3):
        cache.add_pod(build_pod(
            "t", f"g{i}", "", PodPhase.PENDING,
            build_resource_list(cpu="6000m", memory="1Gi"),
            group_name="biggang",
        ))
    return cache


def test_predicate_blocked_gang_verdict():
    cache = _blocked_gang_cache()
    ssn = _run_allocate_tpu(cache)
    try:
        verdict = explain.get_verdict("t/blocked")
        assert verdict is not None
        assert verdict.reason == explain.REASON_PREDICATE
        assert verdict.unassigned == 3
        assert verdict.detail["feasible_nodes"] == 0
        assert verdict.detail["min_available"] == 3
        # Stamped on the session JobInfo too.
        assert ssn.jobs["t/blocked"].last_unschedulable is verdict
        # Reason-labeled metric carries the task count.
        assert metrics.unschedulable_tasks.get(
            (explain.REASON_PREDICATE,)
        ) == 3.0
    finally:
        close_session(ssn)
        cache.shutdown()


def test_minmember_break_gang_verdict():
    cache = _minmember_gang_cache()
    ssn = _run_allocate_tpu(cache)
    try:
        verdict = explain.get_verdict("t/biggang")
        assert verdict is not None
        assert verdict.reason == explain.REASON_GANG
        # Two members allocate (held at the session's gang gate, never
        # dispatched — the job is not Ready); the third cannot fit.
        assert verdict.unassigned == 1
        assert verdict.detail["ready_tasks"] == 2
        assert verdict.detail["min_available"] == 3
        assert "gang needs 3, has 2 ready" in verdict.message
        assert metrics.unschedulable_tasks.get(
            (explain.REASON_GANG,)
        ) == 1.0
    finally:
        close_session(ssn)
        cache.shutdown()


def test_verdict_cleared_when_job_schedulable():
    """A gang that fits leaves no verdict (and a stale one from an
    earlier cycle is dropped)."""
    cache = _cache()
    cache.add_queue(build_queue("default", weight=1))
    cache.add_node(build_node(
        "n1", build_resource_list(cpu="8", memory="16Gi", pods=110)
    ))
    cache.add_pod_group(build_pod_group(
        "ok", namespace="t", min_member=2, queue="default"
    ))
    for i in range(2):
        cache.add_pod(build_pod(
            "t", f"p{i}", "", PodPhase.PENDING,
            build_resource_list(cpu="1000m", memory="1Gi"),
            group_name="ok",
        ))
    ssn = _run_allocate_tpu(cache)
    try:
        assert explain.get_verdict("t/ok") is None
    finally:
        close_session(ssn)
        cache.shutdown()


def test_idle_cycle_clears_stale_verdicts_and_gauge():
    """A job deleted after a verdict was recorded must drop from the
    registry and zero its gauge bucket on the next (idle) cycle, even
    though tensorize has nothing to solve."""
    cache = _blocked_gang_cache()
    ssn = _run_allocate_tpu(cache)
    assert explain.get_verdict("t/blocked") is not None
    assert metrics.unschedulable_tasks.get(
        (explain.REASON_PREDICATE,)
    ) == 3.0
    close_session(ssn)
    # The gang leaves the cluster entirely.
    for i in range(3):
        cache.delete_pod(cache.jobs["t/blocked"].tasks[f"t-b{i}"].pod)
    ssn = _run_allocate_tpu(cache)  # idle: tensorize returns nothing
    try:
        assert explain.get_verdict("t/blocked") is None
        assert metrics.unschedulable_tasks.get(
            (explain.REASON_PREDICATE,)
        ) == 0.0
    finally:
        close_session(ssn)
        cache.shutdown()


def test_diagnose_names_the_blocking_predicate():
    cache = _blocked_gang_cache()
    ssn = _run_allocate_tpu(cache)
    try:
        diag = explain.diagnose_job(ssn, ssn.jobs["t/blocked"])
        rep = diag["representative"]
        assert rep["feasible_nodes"] == 0
        assert rep["blocked_by"] == {"MatchNodeSelector": 2}
        text = explain.format_diagnosis(diag)
        assert "gang needs 3" in text
        assert "0/2 node(s) feasible" in text
        assert "MatchNodeSelector(2)" in text
        assert "predicate-blocked" in text  # the last-cycle verdict
    finally:
        close_session(ssn)
        cache.shutdown()


def test_diagnose_minmember_shortfall():
    cache = _minmember_gang_cache()
    ssn = _run_allocate_tpu(cache)
    try:
        diag = explain.diagnose_job(ssn, ssn.jobs["t/biggang"])
        # Post-apply state: the two allocated members consumed the
        # idle capacity, so the remaining pending member fits nowhere.
        assert diag["representative"]["feasible_nodes"] == 0
        assert diag["representative"]["no_fit_nodes"] == 2
        assert diag["min_available"] == 3
        assert diag["ready_tasks"] == 2
        assert diag["pending_tasks"] == 1
        text = explain.format_diagnosis(diag)
        assert "gang needs 3, has 2 ready" in text
        assert "0/2 node(s) feasible" in text
        assert "2 node(s) pass predicates but lack capacity" in text
        assert "gang-minmember" in text
    finally:
        close_session(ssn)
        cache.shutdown()


def test_explain_cli_offline(tmp_path, capsys):
    state = {
        "queues": [{"name": "default", "weight": 1}],
        "nodes": [
            {"name": "n1",
             "allocatable": {"cpu": "8", "memory": "16Gi", "pods": "110"},
             "labels": {"zone": "a"}},
        ],
        "podGroups": [
            {"name": "stuck", "namespace": "default", "minMember": 2,
             "queue": "default"},
        ],
        "pods": [
            {"name": f"s{i}", "namespace": "default", "group": "stuck",
             "requests": {"cpu": "1000m", "memory": "1Gi"},
             "nodeSelector": {"zone": "nowhere"}}
            for i in range(2)
        ],
    }
    import yaml

    path = tmp_path / "state.yaml"
    path.write_text(yaml.safe_dump(state))
    rc = explain.cli_main(["default/stuck", "--cluster-state", str(path)])
    out = capsys.readouterr().out
    assert rc == 0
    assert "gang needs 2" in out
    assert "MatchNodeSelector(1)" in out


def test_explain_cli_unknown_job(tmp_path, capsys):
    import yaml

    path = tmp_path / "state.yaml"
    path.write_text(yaml.safe_dump({
        "queues": [{"name": "default", "weight": 1}],
        "nodes": [{"name": "n1",
                   "allocatable": {"cpu": "8", "memory": "16Gi",
                                   "pods": "110"}}],
    }))
    rc = explain.cli_main(["default/ghost", "--cluster-state", str(path)])
    assert rc == 3
    assert "not found" in capsys.readouterr().out


def test_debug_jobs_endpoint_serves_verdict():
    from kube_batch_tpu.cli import start_metrics_server

    cache = _blocked_gang_cache()
    ssn = _run_allocate_tpu(cache)
    server, _thread = start_metrics_server("127.0.0.1:0")
    try:
        port = server.server_address[1]
        with urllib.request.urlopen(
            f"http://127.0.0.1:{port}/debug/jobs/t/blocked", timeout=5
        ) as resp:
            doc = json.loads(resp.read().decode())
        assert doc["verdict"]["reason"] == explain.REASON_PREDICATE
        assert doc["verdict"]["unassigned"] == 3
        with urllib.request.urlopen(
            f"http://127.0.0.1:{port}/debug/jobs", timeout=5
        ) as resp:
            listing = json.loads(resp.read().decode())
        assert any(
            v["uid"] == "t/blocked" for v in listing["jobs"]
        )
    finally:
        server.shutdown()
        close_session(ssn)
        cache.shutdown()


def test_victim_note_folds_into_verdict():
    cache = _blocked_gang_cache()
    explain.note_victim_outcome("t/blocked", "preempt", 2, False)
    ssn = _run_allocate_tpu(cache)
    try:
        verdict = explain.get_verdict("t/blocked")
        vs = verdict.detail["victim_selection"]
        assert vs["action"] == "preempt"
        assert vs["victims"] == 2
        assert vs["placed"] is False
    finally:
        close_session(ssn)
        cache.shutdown()
