"""Action-level integration tests with a fake cluster.

Port of the reference pattern (actions/allocate/allocate_test.go:38,
preempt_test.go:37, reclaim_test.go:37): build a real SchedulerCache directly
(no watches) with fake side-effect seams, feed synthetic objects through the
real event-handler entry points, open a real Session with explicit tiers,
run the action, then assert bindings/evictions by draining the fake channels.
"""

import queue as queue_mod


import kube_batch_tpu.actions  # noqa: F401 - registers actions
import kube_batch_tpu.plugins  # noqa: F401 - registers plugins
from kube_batch_tpu.api import PodPhase, TaskStatus, build_resource_list
from kube_batch_tpu.cache import SchedulerCache
from kube_batch_tpu.conf import PluginOption, Tier, apply_plugin_conf_defaults
from kube_batch_tpu.framework import close_session, get_action, open_session
from kube_batch_tpu.utils.test_utils import (
    FakeBinder,
    FakeEvictor,
    FakeStatusUpdater,
    FakeVolumeBinder,
    build_node,
    build_pod,
    build_pod_group,
    build_queue,
)


def make_cache():
    return SchedulerCache(
        binder=FakeBinder(),
        evictor=FakeEvictor(),
        status_updater=FakeStatusUpdater(),
        volume_binder=FakeVolumeBinder(),
    )


def make_tiers(*names_per_tier):
    tiers = []
    for names in names_per_tier:
        opts = []
        for name in names:
            opt = PluginOption(name=name)
            apply_plugin_conf_defaults(opt)
            opts.append(opt)
        tiers.append(Tier(plugins=opts))
    return tiers


DEFAULT_TIERS_ARGS = (
    ["priority", "gang", "conformance"],
    ["drf", "predicates", "proportion", "nodeorder"],
)


def drain(channel, n, timeout=3.0):
    out = []
    for _ in range(n):
        try:
            out.append(channel.get(timeout=timeout))
        except queue_mod.Empty:
            break
    return out


def run_action(cache, action_name, tiers_args=DEFAULT_TIERS_ARGS):
    tiers = make_tiers(*tiers_args)
    ssn = open_session(cache, tiers)
    action, found = get_action(action_name)
    assert found
    action.execute(ssn)
    close_session(ssn)
    return ssn


def req(cpu="1", mem="1Gi"):
    return build_resource_list(cpu=cpu, memory=mem)


class TestAllocate:
    def test_gang_fits_and_binds(self):
        # The example/job.yaml scenario: one PodGroup minMember=3, one queue.
        c = make_cache()
        c.add_queue(build_queue("default"))
        c.add_pod_group(build_pod_group("pg1", namespace="ns", min_member=3))
        for i in range(3):
            c.add_pod(build_pod("ns", f"p{i}", "", PodPhase.PENDING, req(),
                                group_name="pg1"))
        c.add_node(build_node("n1", build_resource_list(cpu="2", memory="4Gi")))
        c.add_node(build_node("n2", build_resource_list(cpu="2", memory="4Gi")))

        run_action(c, "allocate")
        binds = drain(c.binder.channel, 3)
        assert len(binds) == 3
        assert set(c.binder.binds) == {"ns/p0", "ns/p1", "ns/p2"}
        # capacity respected: no node holds more than 2 cpus of binds
        per_node = {}
        for pod_key, host in c.binder.binds.items():
            per_node[host] = per_node.get(host, 0) + 1
        assert all(v <= 2 for v in per_node.values())

    def test_gang_starved_binds_nothing(self):
        # minMember=3 but only 2 cpus in the cluster: all-or-nothing.
        c = make_cache()
        c.add_queue(build_queue("default"))
        c.add_pod_group(build_pod_group("pg1", namespace="ns", min_member=3))
        for i in range(3):
            c.add_pod(build_pod("ns", f"p{i}", "", PodPhase.PENDING, req(),
                                group_name="pg1"))
        c.add_node(build_node("n1", build_resource_list(cpu="2", memory="4Gi")))

        run_action(c, "allocate")
        assert drain(c.binder.channel, 1, timeout=0.3) == []
        assert not c.binder.binds

    def test_two_jobs_share_cluster(self):
        # Reference TestAllocate "two jobs" case: 2 pods each, capacity 2+2.
        c = make_cache()
        c.add_queue(build_queue("default"))
        for g in ("pg1", "pg2"):
            c.add_pod_group(build_pod_group(g, namespace="ns", min_member=1))
            for i in range(2):
                c.add_pod(build_pod("ns", f"{g}-p{i}", "", PodPhase.PENDING,
                                    req(), group_name=g))
        c.add_node(build_node("n1", build_resource_list(cpu="2", memory="4Gi")))
        c.add_node(build_node("n2", build_resource_list(cpu="2", memory="4Gi")))

        run_action(c, "allocate")
        binds = drain(c.binder.channel, 4)
        assert len(binds) == 4

    def test_unschedulable_gang_gets_condition(self):
        c = make_cache()
        c.add_queue(build_queue("default"))
        # Only 2 pods exist for minMember=3: JobValid drops the job with a
        # NotEnoughTasks condition.
        c.add_pod_group(build_pod_group("pg1", namespace="ns", min_member=3))
        for i in range(2):
            c.add_pod(build_pod("ns", f"p{i}", "", PodPhase.PENDING, req(),
                                group_name="pg1"))
        c.add_node(build_node("n1", build_resource_list(cpu="8", memory="8Gi")))

        run_action(c, "allocate")
        assert not c.binder.binds
        conds = c.jobs["ns/pg1"].pod_group.status.conditions
        assert any(cond.reason == "NotEnoughTasks" for cond in conds)

    def test_queue_capacity_multi_tenant(self):
        # Two queues with weights 3:1 over a 4-cpu cluster: proportion
        # gives q1 3 cpus deserved, q2 1 cpu.
        c = make_cache()
        c.add_queue(build_queue("q1", weight=3))
        c.add_queue(build_queue("q2", weight=1))
        for g, q, n in (("pg1", "q1", 4), ("pg2", "q2", 4)):
            c.add_pod_group(build_pod_group(g, namespace="ns", min_member=1,
                                            queue=q))
            for i in range(n):
                c.add_pod(build_pod("ns", f"{g}-p{i}", "", PodPhase.PENDING,
                                    req(mem="10Mi"), group_name=g))
        c.add_node(build_node("n1", build_resource_list(cpu="4", memory="8Gi")))

        run_action(c, "allocate")
        drain(c.binder.channel, 4)
        q1_binds = sum(1 for k in c.binder.binds if k.startswith("ns/pg1"))
        q2_binds = sum(1 for k in c.binder.binds if k.startswith("ns/pg2"))
        assert q1_binds == 3
        assert q2_binds == 1


class TestNodePredicateMemoInvalidation:
    def test_cordoned_node_excluded_after_update(self):
        # The static node verdict is memoized on the watch object
        # (predicates.py batch pass); a node update replaces the object
        # (NodeInfo.set_node), so cordoning between cycles must take
        # effect on the next cycle's mask.
        import copy

        c = make_cache()
        c.add_queue(build_queue("default"))
        node = build_node("n1", build_resource_list(cpu="4", memory="8Gi"))
        c.add_node(node)
        c.add_pod_group(build_pod_group("pg1", namespace="ns", min_member=1))
        c.add_pod(build_pod("ns", "p0", "", PodPhase.PENDING, req(),
                            group_name="pg1"))
        run_action(c, "allocate_tpu")
        assert drain(c.binder.channel, 1) == ["ns/p0"]

        # Cordon via a FRESH object, as a real watch update delivers it.
        cordoned = copy.deepcopy(node)
        cordoned.spec.unschedulable = True
        c.update_node(node, cordoned)
        c.add_pod(build_pod("ns", "p1", "", PodPhase.PENDING, req(),
                            group_name="pg1"))
        run_action(c, "allocate_tpu")
        assert drain(c.binder.channel, 1, timeout=0.3) == []

    def test_inplace_mutation_same_reference_invalidates(self):
        # InProcessCluster.update re-delivers the SAME object reference
        # after in-place mutation; the memo must invalidate via the
        # NodeInfo watch-object generation, not object identity.
        c = make_cache()
        c.add_queue(build_queue("default"))
        node = build_node("n1", build_resource_list(cpu="4", memory="8Gi"))
        c.add_node(node)
        c.add_pod_group(build_pod_group("pg1", namespace="ns", min_member=1))
        c.add_pod(build_pod("ns", "p0", "", PodPhase.PENDING, req(),
                            group_name="pg1"))
        run_action(c, "allocate_tpu")
        assert drain(c.binder.channel, 1) == ["ns/p0"]

        node.spec.unschedulable = True          # in-place
        c.update_node(node, node)               # same reference
        c.add_pod(build_pod("ns", "p1", "", PodPhase.PENDING, req(),
                            group_name="pg1"))
        run_action(c, "allocate_tpu")
        assert drain(c.binder.channel, 1, timeout=0.3) == []


class TestBackfill:
    def test_besteffort_pod_backfilled(self):
        c = make_cache()
        c.add_queue(build_queue("default"))
        c.add_pod_group(build_pod_group("pg1", namespace="ns", min_member=1))
        c.add_pod(build_pod("ns", "be", "", PodPhase.PENDING, {},
                            group_name="pg1"))
        c.add_node(build_node("n1", build_resource_list(cpu="1", memory="1Gi")))

        run_action(c, "backfill")
        assert drain(c.binder.channel, 1) == ["ns/be"]


class TestPreempt:
    def test_high_priority_job_preempts_within_queue(self):
        c = make_cache()
        c.add_queue(build_queue("default"))
        # Low-priority job occupying the whole node.
        c.add_pod_group(build_pod_group("low", namespace="ns", min_member=1))
        c.add_node(build_node("n1", build_resource_list(cpu="2", memory="4Gi")))
        for i in range(2):
            c.add_pod(build_pod("ns", f"low-p{i}", "n1", PodPhase.RUNNING,
                                req(), group_name="low", priority=1))
        # High-priority starving job.
        c.add_pod_group(build_pod_group("high", namespace="ns", min_member=1))
        c.add_pod(build_pod("ns", "high-p0", "", PodPhase.PENDING, req(),
                            group_name="high", priority=100))

        run_action(c, "preempt")
        evicts = drain(c.evictor.channel, 1)
        assert len(evicts) == 1
        assert evicts[0].startswith("ns/low-p")

    def test_no_preemption_when_gang_would_break(self):
        # Victim job has minMember == running count: gang protects it.
        c = make_cache()
        c.add_queue(build_queue("default"))
        c.add_pod_group(build_pod_group("low", namespace="ns", min_member=2))
        c.add_node(build_node("n1", build_resource_list(cpu="2", memory="4Gi")))
        for i in range(2):
            c.add_pod(build_pod("ns", f"low-p{i}", "n1", PodPhase.RUNNING,
                                req(), group_name="low", priority=1))
        c.add_pod_group(build_pod_group("high", namespace="ns", min_member=1))
        c.add_pod(build_pod("ns", "high-p0", "", PodPhase.PENDING, req(),
                            group_name="high", priority=100))

        run_action(c, "preempt")
        assert drain(c.evictor.channel, 1, timeout=0.3) == []


class TestReclaim:
    def test_starving_queue_reclaims_cross_queue(self):
        c = make_cache()
        c.add_queue(build_queue("q1", weight=1))
        c.add_queue(build_queue("q2", weight=1))
        c.add_node(build_node("n1", build_resource_list(cpu="2", memory="4Gi")))
        # q1's job running on the whole cluster.
        c.add_pod_group(build_pod_group("pg1", namespace="ns", min_member=1,
                                        queue="q1"))
        for i in range(2):
            c.add_pod(build_pod("ns", f"pg1-p{i}", "n1", PodPhase.RUNNING,
                                req(), group_name="pg1"))
        # q2 starving.
        c.add_pod_group(build_pod_group("pg2", namespace="ns", min_member=1,
                                        queue="q2"))
        c.add_pod(build_pod("ns", "pg2-p0", "", PodPhase.PENDING, req(),
                            group_name="pg2"))

        run_action(c, "reclaim")
        evicts = drain(c.evictor.channel, 1)
        assert len(evicts) == 1
        assert evicts[0].startswith("ns/pg1-p")

    def test_eviction_moves_capacity_to_releasing_and_pipelines(self):
        # Regression (r5): reclaim must evict a CLONE, not the node's
        # stored task object — session.evict flips status before
        # node.update_task, and NodeInfo.remove_task derives its delta
        # from the stored task's CURRENT status, so evicting the stored
        # object erased the RUNNING→RELEASING capacity move. Observable
        # contract: after reclaim, the victim's capacity sits in
        # node.releasing and the claimant is PIPELINED onto it in the
        # same cycle (not re-evicting next cycle).
        c = make_cache()
        c.add_queue(build_queue("q1", weight=1))
        c.add_queue(build_queue("q2", weight=1))
        c.add_node(build_node("n1", build_resource_list(cpu="2", memory="4Gi")))
        c.add_pod_group(build_pod_group("pg1", namespace="ns", min_member=1,
                                        queue="q1"))
        for i in range(2):
            c.add_pod(build_pod("ns", f"pg1-p{i}", "n1", PodPhase.RUNNING,
                                req(), group_name="pg1"))
        c.add_pod_group(build_pod_group("pg2", namespace="ns", min_member=1,
                                        queue="q2"))
        c.add_pod(build_pod("ns", "pg2-p0", "", PodPhase.PENDING, req(),
                            group_name="pg2"))

        tiers = make_tiers(*DEFAULT_TIERS_ARGS)
        ssn = open_session(c, tiers)
        action, found = get_action("reclaim")
        assert found
        action.execute(ssn)
        try:
            evicts = drain(c.evictor.channel, 1)
            assert len(evicts) == 1
            # The claimant pipelined onto the released capacity in the
            # SAME cycle (no next-cycle re-eviction), and the session
            # mirror is consistent: one victim RELEASING, one RUNNING.
            claimant = next(iter(ssn.jobs["ns/pg2"].tasks.values()))
            assert claimant.status == TaskStatus.PIPELINED
            assert claimant.node_name == "n1"
            statuses = sorted(
                t.status.name for t in ssn.jobs["ns/pg1"].tasks.values()
            )
            assert statuses == ["RELEASING", "RUNNING"]
            # Node accounting: the victim's RUNNING→RELEASING move
            # produced releasing capacity and the pipeline consumed
            # exactly it (broken eviction left releasing at 0 BEFORE
            # the pipeline, which then failed — caught by the PIPELINED
            # assert above); the victim still physically occupies the
            # node until deletion, so used covers victim + survivor +
            # pipelined claimant.
            node = ssn.nodes["n1"]
            assert node.releasing.milli_cpu == 0
            assert node.used.milli_cpu == 3000
        finally:
            close_session(ssn)

    def test_heterogeneous_gang_sim_respects_member_predicates(self):
        # The skip-eviction guard simulates the CLAIMANT's whole gang onto
        # free capacity. With per-member node selectors, a node only the
        # claimant can use must not count for a constrained member —
        # otherwise reclaim skips every cycle while allocate can never
        # place the full gang (under-eviction livelock).
        c = make_cache()
        c.add_queue(build_queue("q1", weight=1))
        c.add_queue(build_queue("q2", weight=1))
        # n1 (zone=a) fully used by q1's running job; n2 (zone=b) free.
        c.add_node(build_node("n1", build_resource_list(cpu="2", memory="4Gi"),
                              labels={"zone": "a"}))
        c.add_node(build_node("n2", build_resource_list(cpu="4", memory="8Gi"),
                              labels={"zone": "b"}))
        c.add_pod_group(build_pod_group("pg1", namespace="ns", min_member=1,
                                        queue="q1"))
        for i in range(2):
            c.add_pod(build_pod("ns", f"pg1-p{i}", "n1", PodPhase.RUNNING,
                                req(), group_name="pg1"))
        # q2's starving gang: claimant is unconstrained (fits free n2),
        # but the second member is pinned to zone=a, where nothing is
        # idle. Free capacity does NOT suffice for the gang → must evict.
        c.add_pod_group(build_pod_group("pg2", namespace="ns", min_member=2,
                                        queue="q2"))
        c.add_pod(build_pod("ns", "pg2-p0", "", PodPhase.PENDING, req(),
                            group_name="pg2"))
        c.add_pod(build_pod("ns", "pg2-p1", "", PodPhase.PENDING, req(),
                            group_name="pg2", selector={"zone": "a"}))

        run_action(c, "reclaim")
        evicts = drain(c.evictor.channel, 1)
        assert len(evicts) == 1
        assert evicts[0].startswith("ns/pg1-p")

    def test_homogeneous_gang_still_skips_when_free_capacity_fits(self):
        # Counterpart: identical specs share one predicate pass and the
        # deliberate skip-eviction divergence still holds — free capacity
        # covers the whole gang, so nothing is evicted.
        c = make_cache()
        c.add_queue(build_queue("q1", weight=1))
        c.add_queue(build_queue("q2", weight=1))
        c.add_node(build_node("n1", build_resource_list(cpu="2", memory="4Gi")))
        c.add_node(build_node("n2", build_resource_list(cpu="4", memory="8Gi")))
        c.add_pod_group(build_pod_group("pg1", namespace="ns", min_member=1,
                                        queue="q1"))
        for i in range(2):
            c.add_pod(build_pod("ns", f"pg1-p{i}", "n1", PodPhase.RUNNING,
                                req(), group_name="pg1"))
        c.add_pod_group(build_pod_group("pg2", namespace="ns", min_member=2,
                                        queue="q2"))
        for i in range(2):
            c.add_pod(build_pod("ns", f"pg2-p{i}", "", PodPhase.PENDING,
                                req(), group_name="pg2"))

        run_action(c, "reclaim")
        assert drain(c.evictor.channel, 1, timeout=0.3) == []


class TestStatementRollback:
    def test_discard_restores_state(self):
        c = make_cache()
        c.add_queue(build_queue("default"))
        c.add_node(build_node("n1", build_resource_list(cpu="2", memory="4Gi")))
        c.add_pod_group(build_pod_group("low", namespace="ns", min_member=1))
        c.add_pod(build_pod("ns", "low-p0", "n1", PodPhase.RUNNING, req(),
                            group_name="low"))
        c.add_pod_group(build_pod_group("high", namespace="ns", min_member=1))
        c.add_pod(build_pod("ns", "high-p0", "", PodPhase.PENDING, req(),
                            group_name="high"))

        tiers = make_tiers(*DEFAULT_TIERS_ARGS)
        ssn = open_session(c, tiers)
        stmt = ssn.statement()
        victim = next(
            t for t in ssn.jobs["ns/low"].tasks.values()
            if t.status == TaskStatus.RUNNING
        )
        claimant = next(iter(ssn.jobs["ns/high"].tasks.values()))
        node = ssn.nodes["n1"]
        idle_before = node.idle.milli_cpu
        releasing_before = node.releasing.milli_cpu

        stmt.evict(victim, "test")
        stmt.pipeline(claimant, "n1")
        assert victim.status == TaskStatus.RELEASING
        assert claimant.status == TaskStatus.PIPELINED

        stmt.discard()
        assert victim.status == TaskStatus.RUNNING
        assert claimant.status == TaskStatus.PENDING
        assert node.idle.milli_cpu == idle_before
        assert node.releasing.milli_cpu == releasing_before
        # nothing hit the cache
        assert not c.evictor.evicts
