"""Observability layer: span tracer, flight recorder, explainability.

Three surfaces over the pipelined scheduling cycle (doc/design/
observability.md):

- ``tracer``: low-overhead hierarchical spans across the cycle's worker
  threads, exported as Chrome trace-event JSON (``KBT_TRACE_DIR``, or
  explicit :func:`export_trace` calls from bench/sim).
- ``flightrecorder``: a fixed-size ring of per-cycle records (phase
  timings, solver stats, verdict counts, errors with tracebacks),
  dumped as canonical JSON on cycle error, SIGUSR1, and the metrics
  server's ``/debug/flightrecorder`` endpoint.
- ``explain``: structured per-job "last unschedulable reason" verdicts
  (predicate-blocked / no-fit / gang minMember / truncated-slab refill
  exhaustion / queue-overused / preempt-reclaim outcomes), behind the
  ``tpu_batch_unschedulable_tasks`` metric, ``/debug/jobs/<ns>/<name>``
  and ``python -m kube_batch_tpu explain``.
- ``telemetry``: long-horizon per-cycle time-series (raw ring + rollup
  windows with count/sum/min/max/quantile-sketch per key) fed from the
  flight record plus resource-watermark probes; served by
  ``/debug/timeseries``, embedded in flight dumps, and consumed by the
  simulator's soak-mode leak/drift detectors (``sim/soak.py``).
- ``quality``: the placement-quality scorecard (packing density,
  fragmentation, fairness distance, disruption churn, solver quality
  rates) computed per cycle from the live cache, served by
  ``/debug/quality``, attached to flight records, and driving the
  ``quality:*`` telemetry series (doc/design/quality.md).
"""

from .flightrecorder import RECORDER, FlightRecorder, install_sigusr1
from .latency import AUDIT, LEDGER, AuditLog, PlacementLedger
from .quality import QUALITY, QualityMonitor
from .telemetry import TELEMETRY, QuantileSketch, Telemetry
from .tracer import TRACER, Tracer, export_trace, span, trace_dir_from_env

__all__ = [
    "AUDIT",
    "AuditLog",
    "LEDGER",
    "FlightRecorder",
    "PlacementLedger",
    "QUALITY",
    "QualityMonitor",
    "QuantileSketch",
    "RECORDER",
    "TELEMETRY",
    "TRACER",
    "Telemetry",
    "Tracer",
    "export_trace",
    "install_sigusr1",
    "span",
    "trace_dir_from_env",
]
