"""In-memory domain model (mirrors reference pkg/scheduler/api)."""

from .cluster_info import ClusterInfo
from .helpers import get_controller_uid, get_task_status, pod_key
from .job_info import JobID, JobInfo, QueueID, TaskID, TaskInfo, get_job_id
from .node_info import NodeInfo, NodeState
from .queue_info import QueueInfo
from .objects import (
    DEFAULT_SCHEDULER_NAME,
    GROUP_NAME_ANNOTATION_KEY,
    NOT_ENOUGH_PODS_REASON,
    NOT_ENOUGH_RESOURCES_REASON,
    POD_GROUP_CONDITION_UNSCHEDULABLE,
    Affinity,
    Container,
    Node,
    NodeCondition,
    NodeSpec,
    NodeStatus,
    ObjectMeta,
    Pod,
    PodCondition,
    PodDisruptionBudget,
    PodGroup,
    PodGroupCondition,
    PodGroupPhase,
    PodGroupSpec,
    PodGroupStatus,
    PodPhase,
    PodSpec,
    PodStatus,
    PriorityClass,
    Queue,
    QueueSpec,
    QueueStatus,
    Taint,
    Toleration,
    generate_uid,
)
from .pod_info import (
    get_pod_resource_request,
    get_pod_resource_without_init_containers,
)
from .serving import (
    CAPACITY_RESERVED,
    CAPACITY_SPOT,
    CAPACITY_TYPE_LABEL_KEY,
    DEFAULT_NODE_CLASS,
    MIN_TOPOLOGY_TIER_ANNOTATION_KEY,
    REPLICA_FLOOR_ANNOTATION_KEY,
    RESERVED_ONLY_ANNOTATION_KEY,
    SLO_SECONDS_ANNOTATION_KEY,
    TOPOLOGY_TIER_LABEL_KEY,
    TPU_GENERATION_LABEL_KEY,
    TPU_GENERATIONS_ANNOTATION_KEY,
    WORKLOAD_CLASS_ANNOTATION_KEY,
    WORKLOAD_CLASS_BATCH,
    WORKLOAD_CLASS_SERVING,
    NodeClass,
    ServingSLO,
    node_class_from_labels,
    parse_serving_slo,
    parse_workload_class,
    slo_permits_node,
)
from .resource_info import (
    GPU_RESOURCE_NAME,
    MIN_MEMORY,
    MIN_MILLI_CPU,
    MIN_MILLI_SCALAR,
    RESOURCE_CPU,
    RESOURCE_MEMORY,
    RESOURCE_PODS,
    TPU_RESOURCE_NAME,
    Resource,
    ResourceList,
    build_resource_list,
    min_resource,
    parse_quantity,
    share,
)
from .types import (
    ALLOCATED_STATUSES,
    NodePhase,
    TaskStatus,
    ValidateResult,
    allocated_status,
)
