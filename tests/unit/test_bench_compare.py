"""Bench regression sentinel (tools/bench_compare.py): canary
normalization, thresholds, allowlist, and the committed-artifact
acceptance pair (BENCH_r05 -> BENCH_r06 passes; an injected 20%
cycle_ms regression fails)."""

import copy
import importlib.util
import json
import os

import pytest

_REPO = os.path.join(os.path.dirname(os.path.abspath(__file__)),
                     os.pardir, os.pardir)
_TOOL = os.path.join(_REPO, "tools", "bench_compare.py")

spec = importlib.util.spec_from_file_location("bench_compare", _TOOL)
bc = importlib.util.module_from_spec(spec)
spec.loader.exec_module(bc)

R05 = os.path.join(_REPO, "BENCH_r05.json")
R06 = os.path.join(_REPO, "BENCH_r06.json")
ALLOW = os.path.join(_REPO, "tools", "bench_allowlist.json")


def base_artifact(value=100.0):
    return {
        "metric": "m",
        "value": value,
        "native_greedy_ms": 1000.0,
        "pods_placed": 50,
        "cycle": {
            "cold": {"cycle_ms": 500.0},
            "steady": {"cycle_ms": 50.0},
            "idle": {"cycle_ms": 10.0},
            "delta": {"cycle_ms": 60.0},
        },
    }


def test_same_machine_regression_flagged():
    old = base_artifact()
    new = copy.deepcopy(old)
    new["cycle"]["idle"]["cycle_ms"] = 12.0  # +20%
    report = bc.compare(old, new)
    assert not report["ok"]
    assert [r["key"] for r in report["regressions"]] == [
        "cycle.idle.cycle_ms"
    ]


def test_improvement_and_noise_pass():
    old = base_artifact()
    new = copy.deepcopy(old)
    new["value"] = 80.0                       # improvement
    new["cycle"]["idle"]["cycle_ms"] = 11.0   # +10% < 15% threshold
    assert bc.compare(old, new)["ok"]


def test_canary_normalization_absorbs_machine_speed():
    """A uniformly 3x slower machine (canary moved 3x too) is not a
    regression; a 3x slowdown with a flat canary is."""
    old = base_artifact()
    slow = copy.deepcopy(old)
    slow["native_greedy_ms"] = 3000.0
    slow["value"] = 300.0
    for s in slow["cycle"].values():
        s["cycle_ms"] *= 3.0
    report = bc.compare(old, slow)
    assert report["canary_scale"] == 3.0
    assert report["cross_machine"]
    assert report["ok"], report["regressions"]

    flat_canary = copy.deepcopy(slow)
    flat_canary["native_greedy_ms"] = 1000.0
    report = bc.compare(old, flat_canary)
    assert not report["ok"]


def test_canary_key_not_self_normalized():
    """``greedy_small_ms`` is both a policy row and a canary: its own
    row must be normalized by the OTHER canary, never by itself — a
    self-normalized ratio is tautologically 1.0 and a pure-Python
    greedy regression would be invisible (and would silently loosen
    every other normalized threshold via the max-over-canaries
    scale)."""
    old = base_artifact()
    old["greedy_small_ms"] = 800.0
    new = copy.deepcopy(old)
    new["greedy_small_ms"] = 1600.0  # 2x slower, native canary flat
    report = bc.compare(old, new)
    assert not report["ok"]
    assert "greedy_small_ms" in [r["key"] for r in report["regressions"]]
    row = next(r for r in report["rows"] if r["key"] == "greedy_small_ms")
    assert row["normalized_ratio"] == 2.0
    # Cross-machine: a uniformly 3x slower machine (BOTH canaries moved
    # 3x) explains the greedy movement — not a regression. And a round
    # where only the OTHER canary moved (the r06 contention-polluted
    # native measurement) must not drag a flat greedy row into a false
    # positive: the raw same-machine view explains it.
    slow = copy.deepcopy(old)
    slow["native_greedy_ms"] = 3000.0
    slow["greedy_small_ms"] = 2400.0
    assert bc.compare(old, slow)["ok"]
    polluted = copy.deepcopy(old)
    polluted["native_greedy_ms"] = 250.0   # native 4x "faster"
    polluted["greedy_small_ms"] = 790.0    # greedy flat (raw ~0.99)
    report = bc.compare(old, polluted)
    assert "greedy_small_ms" not in [
        r["key"] for r in report["regressions"]
    ]


def test_count_must_not_drop():
    old = base_artifact()
    new = copy.deepcopy(old)
    new["pods_placed"] = 49
    report = bc.compare(old, new)
    assert [r["key"] for r in report["regressions"]] == ["pods_placed"]


def test_allowlist_globs_and_reasons():
    old = base_artifact()
    new = copy.deepcopy(old)
    new["cycle"]["steady"]["cycle_ms"] = 200.0
    report = bc.compare(old, new, allowed={
        "cycle.steady.*": "intentional: tracked in ROADMAP"
    })
    assert report["ok"]
    assert report["allowed"][0]["key"] == "cycle.steady.cycle_ms"
    assert "ROADMAP" in report["allowed"][0]["allow_reason"]


def test_allowlist_file_requires_reason(tmp_path):
    bad = tmp_path / "allow.json"
    bad.write_text(json.dumps([{"key": "value"}]))
    with pytest.raises(ValueError):
        bc.load_allowlist(str(bad), [])


def test_missing_keys_skipped_not_failed():
    old = {"metric": "m", "value": 100.0}
    new = {"metric": "m", "value": 90.0}
    report = bc.compare(old, new)
    assert report["ok"]
    skipped = [r for r in report["rows"] if r["status"] == "skipped"]
    assert skipped  # everything but `value`


def test_parsed_wrapper_unwrapped():
    data = bc.load_bench(R05)
    assert data["metric"].startswith("gang-cycle")


# The r06 steady-cycle regression was FIXED in PR 8 (warm-started
# steady cycles), so its allowlist entry is retired from the committed
# file; the historical r05→r06 pair still needs it, carried inline.
_HISTORICAL_ALLOW = {
    "cycle.steady.cycle_ms": (
        "historical r06 full-tensorize-rebuild regression, fixed by the "
        "warm-start work (PR 8)"
    ),
}


def test_committed_r05_r06_passes_with_historical_allow():
    """The acceptance pair: the two committed artifacts pass with the
    (now retired, inline) steady-cycle allow entry."""
    report = bc.compare(bc.load_bench(R05), bc.load_bench(R06),
                        allowed=dict(_HISTORICAL_ALLOW))
    assert report["ok"], report["regressions"]
    assert [r["key"] for r in report["allowed"]] == [
        "cycle.steady.cycle_ms"
    ]


def test_committed_allowlist_no_longer_carries_steady_entry():
    """PR 8 acceptance: the cycle.steady.cycle_ms allowlist entry is
    DELETED — the steady cycle is fixed, and bench-compare must stay
    green without it from r08 on."""
    allowed = bc.load_allowlist(ALLOW, [])
    assert "cycle.steady.cycle_ms" not in allowed


def test_committed_r05_r06_fails_without_allowlist():
    """The allowlist is load-bearing: the steady regression is real."""
    report = bc.compare(bc.load_bench(R05), bc.load_bench(R06))
    assert not report["ok"]
    assert [r["key"] for r in report["regressions"]] == [
        "cycle.steady.cycle_ms"
    ]


def test_injected_regression_flagged_cli(tmp_path):
    """The CI self-test path: 20% cycle_ms injection must exit 0 from
    --self-test (which internally asserts the injection IS flagged)."""
    allow = tmp_path / "allow.json"
    allow.write_text(json.dumps([
        {"key": k, "reason": v} for k, v in _HISTORICAL_ALLOW.items()
    ]))
    rc = bc.main([R05, R06, "--self-test", "--allow-file", str(allow)])
    assert rc == 0


def test_cli_exit_codes(tmp_path):
    allow = tmp_path / "allow.json"
    allow.write_text(json.dumps([
        {"key": k, "reason": v} for k, v in _HISTORICAL_ALLOW.items()
    ]))
    assert bc.main([R05, R06, "--allow-file", str(allow)]) == 0
    assert bc.main([R05, R06]) == 1
    assert bc.main(["/nonexistent.json", R06]) == 2
